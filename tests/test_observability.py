"""Job-level observability tests (telemetry/collector.py + postmortem).

The contracts a postmortem actually leans on:

- **Timeline merge under clock skew**: per-host offsets (anchored once
  per boot at bootstrap) shift every worker record onto the
  controller's clock, so a ±5s skew between hosts cannot reorder cause
  and effect in the merged timeline. Raw timestamps are preserved.
- **Goodput ledger**: a clean drain (emergency checkpoint at the drain
  step) loses NOTHING; a hard death re-executes the steps past the last
  durable checkpoint and the ledger charges exactly those.
- **Metrics federation**: counters sum, throughput gauges sum, level
  gauges max, histograms merge bucket-wise — and a pod the scraper
  cannot reach is VISIBLE (up 0, failures counted), not silently
  absent. Exercised end to end: a real TPUJobController reconciling
  through the wire-level fake kube API server, scraping real worker
  /metrics listeners, re-exported through the controller's own
  MetricsServer.
- **Postmortem CLI**: renders a lifecycle report from timeline.jsonl,
  exits nonzero when the timeline is empty or unparseable.
"""
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from mpi_operator_tpu.telemetry import WorkerTelemetry
from mpi_operator_tpu.telemetry.collector import (
    ClockSync,
    JobObservatory,
    MetricsFederation,
    goodput_ledger,
    merge_timeline,
    parse_prometheus,
)
from mpi_operator_tpu.telemetry.collector import main as collector_main
from mpi_operator_tpu import postmortem

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fake_kube_apiserver import FakeKubeAPIServer  # noqa: E402


# ---------------------------------------------------------------------------
# clock-offset correction + timeline merge
# ---------------------------------------------------------------------------

def _rec(ts, event, **f):
    return {"ts": ts, "event": event, **f}


def test_merge_timeline_corrects_five_second_skew(tmp_path):
    """Two synthetic hosts, one +5s fast and one -5s slow against the
    controller clock. The TRUE order is interleaved; raw timestamps
    would garble it; per-host offsets must restore it exactly."""
    # true controller-clock times: a@100 (fast host), c@101 (controller),
    # b@102 (slow host), r@103 (controller)
    controller = [_rec(101.0, "gang_restart", restart=1),
                  _rec(103.0, "pods_ready")]
    fast = [_rec(105.0, "preemption_drain", step=5)]    # clock reads +5
    slow = [_rec(97.0, "emergency_checkpoint", step=5)]  # clock reads -5
    out = str(tmp_path / "timeline.jsonl")
    merged = merge_timeline(
        [(None, controller), ("fast:9100", fast), ("slow:9100", slow)],
        offsets={"fast:9100": -5.0, "slow:9100": +5.0},
        out_path=out)
    assert [r["event"] for r in merged] == [
        "preemption_drain", "gang_restart", "emergency_checkpoint",
        "pods_ready"]
    ts = [r["ts"] for r in merged]
    assert ts == sorted(ts) == [100.0, 101.0, 102.0, 103.0]
    # corrected records keep the evidence: raw ts + applied offset + host
    drain = merged[0]
    assert drain["ts_raw"] == 105.0 and drain["clock_offset"] == -5.0
    assert drain["host"] == "fast:9100"
    assert merged[1]["host"] == "controller"
    # the on-disk timeline is the same records, one JSON object per line
    with open(out) as fh:
        on_disk = [json.loads(line) for line in fh]
    assert on_disk == merged


def test_clock_sync_pins_offset_per_boot():
    cs = ClockSync()
    cs.note("h:9100", local_now=100.0, remote_now=105.0, boot_id="b1")
    assert cs.offset("h:9100") == -5.0
    # later scrapes of the SAME boot must not re-pin (network jitter in
    # the later samples would smear the correction)
    cs.note("h:9100", local_now=200.0, remote_now=209.0, boot_id="b1")
    assert cs.offset("h:9100") == -5.0
    # a new boot (pod restart) re-anchors
    cs.note("h:9100", local_now=300.0, remote_now=298.0, boot_id="b2")
    assert cs.offset("h:9100") == 2.0
    assert cs.offset("unknown") == 0.0


# ---------------------------------------------------------------------------
# the goodput ledger
# ---------------------------------------------------------------------------

def test_ledger_clean_drain_loses_nothing():
    records = [
        _rec(1.0, "preemption_drain", step=5),
        _rec(1.1, "emergency_checkpoint", step=5),
        _rec(2.0, "gang_restart", exit_code=215, restart=1),
        _rec(3.0, "checkpoint_restore", step=5),     # restore == frontier
        _rec(4.0, "run_complete", step=8),
    ]
    led = goodput_ledger(records)
    assert led["lost_steps"] == 0
    assert led["useful_steps"] == 8
    assert led["goodput"] == 1.0
    assert led["restarts"] == 1


def test_ledger_hard_death_charges_reexecuted_steps():
    """The tier1 --resilience shape: drain at 5 (lossless), finish at 8,
    hard death at 11 with last checkpoint 8, finish at 12 →
    re-executed 9-11 = 3 lost, 12 useful, goodput 0.8."""
    records = [
        _rec(1.0, "preemption_drain", step=5),
        _rec(1.1, "emergency_checkpoint", step=5),
        _rec(2.0, "gang_restart", exit_code=215, restart=1),
        _rec(3.0, "checkpoint_restore", step=5),
        _rec(4.0, "run_complete", step=8),
        _rec(5.0, "checkpoint_restore", step=8),
        _rec(6.0, "fault_injected", fault="die", step=11),
        _rec(7.0, "gang_restart", exit_code=217, restart=2),
        _rec(8.0, "checkpoint_restore", step=8),     # 9-11 re-run
        _rec(9.0, "run_complete", step=12),
    ]
    led = goodput_ledger(records)
    assert led["lost_steps"] == 3
    assert led["useful_steps"] == 12
    assert led["goodput"] == pytest.approx(0.8)
    assert led["restarts"] == 2


def test_ledger_rollback_charges_rewound_steps():
    records = [
        _rec(1.0, "run_complete", step=4),
        _rec(2.0, "divergence_rollback", from_step=7, to_step=4),
        _rec(3.0, "run_complete", step=9),
    ]
    led = goodput_ledger(records)
    assert led["lost_steps"] == 3
    assert led["useful_steps"] == 9
    assert led["rollbacks"] == 1


def test_ledger_empty_is_perfect():
    led = goodput_ledger([])
    assert led["goodput"] == 1.0
    assert led["lost_steps"] == 0


# ---------------------------------------------------------------------------
# federation aggregation (pure)
# ---------------------------------------------------------------------------

POD0 = """\
# HELP tpu_worker_steps_total train steps executed
# TYPE tpu_worker_steps_total counter
tpu_worker_steps_total 100
# TYPE tpu_worker_step gauge
tpu_worker_step 7
# TYPE tpu_worker_tokens_per_sec gauge
tpu_worker_tokens_per_sec 1000.5
# TYPE tpu_worker_step_seconds histogram
tpu_worker_step_seconds_bucket{le="0.1"} 3
tpu_worker_step_seconds_bucket{le="+Inf"} 5
tpu_worker_step_seconds_sum 0.4
tpu_worker_step_seconds_count 5
tpu_operator_syncs_total 9
"""

POD1 = """\
# TYPE tpu_worker_steps_total counter
tpu_worker_steps_total 40
# TYPE tpu_worker_step gauge
tpu_worker_step 9
# TYPE tpu_worker_tokens_per_sec gauge
tpu_worker_tokens_per_sec 999.5
# TYPE tpu_worker_step_seconds histogram
tpu_worker_step_seconds_bucket{le="0.1"} 1
tpu_worker_step_seconds_bucket{le="+Inf"} 2
tpu_worker_step_seconds_sum 0.3
tpu_worker_step_seconds_count 2
"""


def test_parse_prometheus_labels_and_types():
    samples, types = parse_prometheus(
        '# TYPE m counter\nm{a="x\\"y",b="z"} 4\nnot a sample\n')
    assert samples == [("m", {"a": 'x"y', "b": "z"}, 4.0)]
    assert types["m"] == "counter"


def test_federation_sums_maxes_and_merges():
    fed = MetricsFederation("trainjob", clock=lambda: 50.0)
    fed.ingest(0, POD0)
    fed.ingest(1, POD1)
    text = "\n".join(fed.render_lines())
    # counters sum across the gang; level gauges take the max;
    # throughput (_per_sec) gauges sum; histograms merge bucket-wise
    assert 'tpu_job_steps_total{job="trainjob"} 140' in text
    assert 'tpu_job_step{job="trainjob"} 9' in text
    assert 'tpu_job_tokens_per_sec{job="trainjob"} 2000' in text
    assert 'tpu_job_step_seconds_bucket{job="trainjob",le="0.1"} 4' in text
    assert 'tpu_job_step_seconds_count{job="trainjob"} 7' in text
    # operator series do NOT re-federate
    assert "tpu_job_syncs_total" not in text
    # both pods healthy
    assert 'tpu_job_up{job="trainjob",replica_rank="0"} 1' in text
    assert 'tpu_job_up{job="trainjob",replica_rank="1"} 1' in text
    assert fed.observed_step() == 9


def test_federation_failed_scrape_is_visible():
    clock = [100.0]
    fed = MetricsFederation("trainjob", clock=lambda: clock[0])
    fed.ingest(0, POD0)
    clock[0] = 130.0
    fed.scrape_failed(0)
    text = "\n".join(fed.render_lines())
    assert 'tpu_job_up{job="trainjob",replica_rank="0"} 0' in text
    assert ('tpu_job_scrape_failures_total{job="trainjob",'
            'replica_rank="0"} 1' in text)
    assert ('tpu_job_scrape_staleness_seconds{job="trainjob",'
            'replica_rank="0"} 30' in text)


# ---------------------------------------------------------------------------
# federation end to end: controller over the wire-level fake kube API
# server, scraping real worker /metrics listeners
# ---------------------------------------------------------------------------

def _http(url: str) -> str:
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.read().decode()


def test_federation_over_fake_kube_apiserver(tmp_path):
    from mpi_operator_tpu.api.types import new_tpu_job
    from mpi_operator_tpu.cluster.kubeclient import KubeAPIServer, KubeConfig
    from mpi_operator_tpu.controller import (ControllerConfig,
                                             TPUJobController)
    from mpi_operator_tpu.controller.metrics import MetricsServer

    fake = FakeKubeAPIServer().start()
    kube = KubeAPIServer(KubeConfig(server=fake.url),
                         request_timeout=5.0, watch_timeout_seconds=2)
    stop = threading.Event()
    controller = None
    metrics_srv = None
    workers = []
    try:
        controller = TPUJobController(
            kube, config=ControllerConfig(worker_metrics_port=1,
                                          events_dir=str(tmp_path),
                                          scrape_interval=0.0))
        assert controller.observatory is not None   # config switched it on
        controller.run(threadiness=1, stop_event=stop)
        job = new_tpu_job("trainjob", tpus=8)
        job.spec.template.main_container().image = "tpu-bench:latest"
        kube.create(job)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if fake.get_object("statefulsets", "default", "trainjob-worker"):
                break
            time.sleep(0.02)
        else:
            raise TimeoutError("controller never reconciled the job")
        # the sync recorded job_created on the controller's own event log
        obs = controller.observatory
        assert obs.view("trainjob")["created"]

        # two real worker /metrics listeners stand in for the pods (the
        # fake API server hosts no kubelet, so the pod DNS names the
        # controller would scrape in-cluster don't resolve here)
        targets = {}
        for rank, step in ((0, 7), (1, 9)):
            wt = WorkerTelemetry()
            wt.train.update_window(tokens_per_sec=1000.0, step=step)
            srv = wt.serve(port=0, host="127.0.0.1")
            workers.append((wt, srv))
            targets[rank] = f"http://127.0.0.1:{srv.port}"
        obs.observe("trainjob", targets, force=True)

        # the federated series ride the controller's OWN /metrics scrape
        metrics_srv = MetricsServer(controller, port=0, host="127.0.0.1")
        text = _http(f"http://127.0.0.1:{metrics_srv.port}/metrics")
        assert "tpu_operator_syncs_total" in text
        assert 'tpu_job_step{job="trainjob"} 9' in text
        assert 'tpu_job_tokens_per_sec{job="trainjob"} 2000' in text
        assert 'tpu_job_up{job="trainjob",replica_rank="0"} 1' in text
        assert 'tpu_job_up{job="trainjob",replica_rank="1"} 1' in text
        assert 'tpu_job_goodput{job="trainjob"} 1' in text

        # kill pod 0 and re-observe: the dead pod must be VISIBLE
        workers[0][1].close()
        obs.observe("trainjob", targets, force=True)
        text = _http(f"http://127.0.0.1:{metrics_srv.port}/metrics")
        assert 'tpu_job_up{job="trainjob",replica_rank="0"} 0' in text
        assert ('tpu_job_scrape_failures_total{job="trainjob",'
                'replica_rank="0"} 1' in text)
        assert 'tpu_job_up{job="trainjob",replica_rank="1"} 1' in text
    finally:
        stop.set()
        controller and controller.queue.shut_down()
        for wt, srv in workers:
            srv.close()
            wt.close()
        metrics_srv and metrics_srv.close()
        kube.stop()
        fake.stop()


# ---------------------------------------------------------------------------
# observatory: /events scrape -> clock anchor -> merged timeline
# ---------------------------------------------------------------------------

def test_observatory_scrapes_events_and_writes_timeline(tmp_path):
    from mpi_operator_tpu.telemetry import EventLog

    worker_log = EventLog(str(tmp_path / "worker" / "events.jsonl"))
    worker_log.emit("clock_anchor", boot_id="boot1", process_id=0)
    worker_log.emit("preemption_drain", step=5)
    wt = WorkerTelemetry(events=worker_log)
    wt.train.update_window(step=5)
    srv = wt.serve(port=0, host="127.0.0.1")
    try:
        obs = JobObservatory(events_dir=str(tmp_path / "op"),
                             scrape_interval=0.0)
        obs.note_created("trainjob", namespace="default", tpus=8)
        obs.observe("trainjob", {0: f"http://127.0.0.1:{srv.port}"},
                    force=True)
        # the /events payload's server-side "now" + the clock_anchor's
        # boot_id pin this host's offset (≈0 here — same machine); every
        # merged worker record carries the correction evidence
        merged = obs.merged_records("trainjob")
        events = [r["event"] for r in merged]
        assert "preemption_drain" in events and "job_created" in events
        drain = merged[events.index("preemption_drain")]
        assert drain["host"].startswith("127.0.0.1:")
        assert drain["ts"] == pytest.approx(
            drain["ts_raw"] + drain["clock_offset"])
        assert abs(drain["clock_offset"]) < 5.0
        # scraping a live step also emits first_step_observed exactly once
        obs.observe("trainjob", {0: f"http://127.0.0.1:{srv.port}"},
                    force=True)
        firsts = [r for r in obs.view("trainjob")["controller_records"]
                  if r["event"] == "first_step_observed"]
        assert len(firsts) == 1
        # terminal note writes <events_dir>/<job>/timeline.jsonl
        obs.note_terminal("trainjob", succeeded=True)
        out = os.path.join(str(tmp_path / "op"), "trainjob",
                           "timeline.jsonl")
        with open(out) as fh:
            lines = [json.loads(line) for line in fh]
        ts = [r["ts"] for r in lines]
        assert ts == sorted(ts) and len(lines) >= 4
        obs.close()
    finally:
        srv.close()
        wt.close()


# ---------------------------------------------------------------------------
# collector CLI round-trip + postmortem CLI
# ---------------------------------------------------------------------------

def test_collector_cli_emit_merge_and_postmortem(tmp_path, capsys):
    ctl = str(tmp_path / "controller.jsonl")
    wrk = str(tmp_path / "events.jsonl")
    for argv in (
        ["emit", "--log", ctl, "--job", "j", "job_created", "tpus=8"],
        ["emit", "--log", wrk, "--job", "j", "emergency_checkpoint",
         "step=5"],
        ["emit", "--log", wrk, "--job", "j", "fault_injected", "step=11"],
        ["emit", "--log", ctl, "--job", "j", "gang_restart",
         "exit_code=217", "restart=1"],
        ["emit", "--log", wrk, "--job", "j", "checkpoint_restore",
         "step=8"],
        ["emit", "--log", wrk, "--job", "j", "run_complete", "step=12"],
    ):
        assert collector_main(argv) == 0
    out = str(tmp_path / "timeline.jsonl")
    prom = str(tmp_path / "federated.prom")
    assert collector_main(["merge", "--job", "j", "--controller", ctl,
                           "--worker", f"w0={wrk}", "--out", out,
                           "--metrics-out", prom]) == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["lost_steps"] == 3 and summary["useful_steps"] == 12
    with open(prom) as fh:
        text = fh.read()
    assert 'tpu_job_steps_lost_total{job="j"} 3' in text
    assert 'tpu_job_goodput{job="j"} 0.8' in text

    # postmortem renders it and the ledger numbers agree
    assert postmortem.main([out]) == 0
    report = capsys.readouterr().out
    assert "gang_restart" in report and "goodput" in report
    assert "0.8000" in report

    # empty and unparseable timelines are a nonzero exit
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert postmortem.main([str(empty)]) == 2
    garbage = tmp_path / "garbage.jsonl"
    garbage.write_text("not json\nstill not json\n")
    assert postmortem.main([str(garbage)]) == 2
    assert postmortem.main(["--json", out]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["ledger"]["lost_steps"] == 3


# ---------------------------------------------------------------------------
# timeline rotation (TPU_TIMELINE_MAX_BYTES) + drain latency
# ---------------------------------------------------------------------------

def test_timeline_rotation_spans_chain(tmp_path, monkeypatch):
    """With TPU_TIMELINE_MAX_BYTES set, write_timeline appends
    incrementally (no duplicates across calls) and rotates through the
    events.py .N chain; both read_events and postmortem.read_timeline
    see every record across the generations."""
    from mpi_operator_tpu.telemetry.events import event_files, read_events

    monkeypatch.setenv("TPU_TIMELINE_MAX_BYTES", "600")
    monkeypatch.setenv("TPU_TIMELINE_KEEP", "10")
    obs = JobObservatory(events_dir=str(tmp_path), scrape_interval=0.0)
    obs.note_created("j", tpus=8)
    out = None
    for step in range(40):
        obs.record("j", "window_stats", step=step)
        out = obs.write_timeline("j")
    assert os.path.getsize(out) <= 600
    assert len(event_files(out)) >= 2        # the cap actually rotated
    for records in (read_events(out), postmortem.read_timeline(out)):
        steps = [r["step"] for r in records
                 if r.get("event") == "window_stats"]
        assert sorted(steps) == list(range(40))   # complete, no dupes
        assert any(r.get("event") == "job_created" for r in records)
    obs.close()


def test_timeline_uncapped_rewrite_unchanged(tmp_path, monkeypatch):
    """Without the env cap the historical behaviour holds: one atomic
    full rewrite per call, no .N files."""
    from mpi_operator_tpu.telemetry.events import event_files

    monkeypatch.delenv("TPU_TIMELINE_MAX_BYTES", raising=False)
    obs = JobObservatory(events_dir=str(tmp_path), scrape_interval=0.0)
    obs.note_created("j", tpus=8)
    for step in range(10):
        obs.record("j", "window_stats", step=step)
        out = obs.write_timeline("j")
    assert event_files(out) == [out]
    assert len(postmortem.read_timeline(out)) == 11
    obs.close()


def test_postmortem_drain_latency(tmp_path, capsys):
    """preemption_drain -> same host's next emergency_checkpoint delta
    is computed per host and surfaced in both the summary and the
    rendered report; an unpaired checkpoint gets no latency."""
    path = tmp_path / "timeline.jsonl"
    recs = [
        _rec(0.0, "job_created", job="j", host="controller"),
        _rec(1.0, "preemption_drain", step=5, host="w0"),
        _rec(2.0, "emergency_checkpoint", step=7, host="w1"),  # unpaired
        _rec(3.5, "emergency_checkpoint", step=5, host="w0"),
        _rec(4.0, "job_failed", host="controller"),
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    summary = postmortem.summarize(postmortem.read_timeline(str(path)))
    assert summary["drain_latencies"] == [
        {"t": 3.5, "host": "w0", "seconds": 2.5}]
    paired = [i for i in summary["incidents"]
              if i.get("drain_seconds") is not None]
    assert len(paired) == 1 and paired[0]["host"] == "w0"

    assert postmortem.main([str(path)]) == 0
    report = capsys.readouterr().out
    assert "drain latency: 1 preemption drain(s)" in report
    assert "(drain->ckpt 2.5s)" in report
