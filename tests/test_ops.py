"""Pallas flash-attention kernel tests (interpret mode on CPU — the same
kernel code path that compiles to Mosaic on TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_operator_tpu.models.transformer import dense_attention
from mpi_operator_tpu.ops.attention import flash_attention


def _qkv(B=2, S=128, H=2, D=16, dtype=jnp.float32):
    return tuple(
        jax.random.normal(jax.random.PRNGKey(i), (B, S, H, D), dtype)
        for i in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal, dtype=jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_flash_multiple_block_sizes():
    q, k, v = _qkv(S=256)
    ref = dense_attention(q, k, v, causal=True, dtype=jnp.float32)
    for bq, bk in [(64, 128), (128, 64), (256, 256)]:
        out = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5)


def test_flash_gradients_match_dense():
    q, k, v = _qkv(S=64)

    def lf(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                block_q=32, block_k=32) ** 2).sum()

    def ld(q, k, v):
        return (dense_attention(q, k, v, causal=True,
                                dtype=jnp.float32) ** 2).sum()

    g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_fallback_on_odd_lengths():
    """S that doesn't tile falls back to dense — still correct."""
    q, k, v = _qkv(S=100)
    ref = dense_attention(q, k, v, causal=True, dtype=jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_flash_under_jit():
    q, k, v = _qkv(S=64)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                block_q=32, block_k=32))
    out = f(q, k, v)
    ref = dense_attention(q, k, v, causal=True, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def _padding_mask(B=2, S=128, valid=96):
    # batch row 0 padded to `valid` tokens, row 1 full
    mask = np.ones((B, S), bool)
    mask[0, valid:] = False
    return jnp.asarray(mask)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_padding_mask_matches_dense(causal):
    """The round-1 gap: padded BERT batches must keep the flash path."""
    q, k, v = _qkv()
    mask = _padding_mask()
    ref = dense_attention(q, k, v, mask=mask, causal=causal,
                          dtype=jnp.float32)
    out = flash_attention(q, k, v, causal=causal, mask=mask,
                          block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_flash_masked_gradients_match_dense():
    q, k, v = _qkv()
    mask = _padding_mask()
    # score only valid query rows, as a real masked loss does
    w = mask.astype(jnp.float32)[:, :, None, None]

    def lf(q, k, v):
        return ((flash_attention(q, k, v, causal=False, mask=mask,
                                 block_q=64, block_k=64) * w) ** 2).sum()

    def ld(q, k, v):
        return ((dense_attention(q, k, v, mask=mask, causal=False,
                                 dtype=jnp.float32) * w) ** 2).sum()

    g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_fully_masked_row_is_finite():
    """A batch row whose keys are ALL padding must produce zeros/finite
    grads, not NaNs (degenerate lse guard in the backward kernels)."""
    q, k, v = _qkv()
    mask = jnp.asarray(np.stack([np.zeros(128, bool), np.ones(128, bool)]))

    def lf(q, k, v):
        return (flash_attention(q, k, v, causal=False, mask=mask,
                                block_q=64, block_k=64) ** 2).sum()

    out = flash_attention(q, k, v, causal=False, mask=mask,
                          block_q=64, block_k=64)
    assert np.isfinite(np.asarray(out)).all()
    grads = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()


def test_bert_model_keeps_flash_with_mask():
    """models._attend must NOT fall back to dense for masked flash."""
    from unittest import mock

    from mpi_operator_tpu.models import transformer as tr

    cfg = tr.TransformerConfig(causal=False, attention="flash",
                               dtype=jnp.float32, num_heads=2, embed_dim=32,
                               vocab_size=64, max_len=128)
    q, k, v = _qkv(D=16)
    mask = _padding_mask()
    with mock.patch.object(tr, "dense_attention",
                           side_effect=AssertionError("fell back to dense")):
        out = tr._attend(q, k, v, mask, cfg)
    assert out.shape == q.shape


def test_auto_tile_policy_never_demotes_to_dense():
    """Seq lens that are 512-multiples but not 1024-multiples (2560,
    3584, ...) must keep 512 flash tiles — the 1024 auto tiles apply only
    when they divide S exactly (falling through to dense attention at
    long seq would OOM on a real chip)."""
    import numpy as np

    from mpi_operator_tpu.ops.attention import flash_attention
    from mpi_operator_tpu.models.transformer import dense_attention

    B, S, H, D = 1, 2560, 2, 8
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, S, H, D),
                                 jnp.float32) for i in range(3))
    out = flash_attention(q, k, v, causal=True)      # must take flash path
    ref = dense_attention(q, k, v, causal=True, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3)
