"""Pallas flash-attention kernel tests (interpret mode on CPU — the same
kernel code path that compiles to Mosaic on TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_operator_tpu.models.transformer import dense_attention
from mpi_operator_tpu.ops.attention import flash_attention


def _qkv(B=2, S=128, H=2, D=16, dtype=jnp.float32):
    return tuple(
        jax.random.normal(jax.random.PRNGKey(i), (B, S, H, D), dtype)
        for i in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal, dtype=jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_flash_multiple_block_sizes():
    q, k, v = _qkv(S=256)
    ref = dense_attention(q, k, v, causal=True, dtype=jnp.float32)
    for bq, bk in [(64, 128), (128, 64), (256, 256)]:
        out = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5)


def test_flash_gradients_match_dense():
    q, k, v = _qkv(S=64)

    def lf(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                block_q=32, block_k=32) ** 2).sum()

    def ld(q, k, v):
        return (dense_attention(q, k, v, causal=True,
                                dtype=jnp.float32) ** 2).sum()

    g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_fallback_on_odd_lengths():
    """S that doesn't tile falls back to dense — still correct."""
    q, k, v = _qkv(S=100)
    ref = dense_attention(q, k, v, causal=True, dtype=jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_flash_under_jit():
    q, k, v = _qkv(S=64)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                block_q=32, block_k=32))
    out = f(q, k, v)
    ref = dense_attention(q, k, v, causal=True, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)
