"""Paged KV cache tests: the block-table serving layout + prefix cache.

Three layers, each pinned against the layer below it:

- `PageAllocator` (serve/slots.py): free-list accounting, refcounted
  prefix chains, LRU eviction with descendant cascade — unit tests plus
  a randomized admit/publish/retire fuzz with the invariant audit
  (`check()`) after every operation.
- `paged_decode_attention` (ops/attention.py): the Pallas kernel over a
  page pool must match the gathered dense oracle, with POISON in every
  page slot past each row's cursor so any stray read is loud.
- The paged `ServingEngine` (`EngineConfig.paged`): token-exact against
  the CONTIGUOUS engine — same model, same trace, both attention paths —
  including prefix-cache hits, slot reuse, int8 caches, and the capacity
  claim (more concurrent requests than contiguous under the same cache
  byte budget).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import meta

from mpi_operator_tpu.models import CausalLM, gpt2_config
from mpi_operator_tpu.ops.attention import paged_decode_attention
from mpi_operator_tpu.serve import (
    EngineConfig, PageAllocator, Request, Scheduler, ServingEngine,
    plan_chunks,
)

pytestmark = pytest.mark.serving

POISON = 1e4


# ---------------------------------------------------------------------------
# PageAllocator (no jax)
# ---------------------------------------------------------------------------

def test_page_allocator_lifecycle_and_errors():
    with pytest.raises(ValueError, match="trash"):
        PageAllocator(1, 4)
    a = PageAllocator(5, 4)                  # pages 1..4 usable
    assert a.usable == 4 and a.available == 4 and a.in_use == 0
    p1, p2 = a.alloc(), a.alloc()
    assert p1 != p2 and a.in_use == 2
    a.release(p1)
    assert a.available == 3
    with pytest.raises(RuntimeError, match="double-free"):
        a.release(p1)
    with pytest.raises(ValueError, match="trash"):
        a.release(a.TRASH)
    a.alloc(), a.alloc(), a.alloc()
    with pytest.raises(RuntimeError, match="out of KV pages"):
        a.alloc()                            # 4 live, nothing evictable
    a.check()


def test_page_allocator_prefix_chain_and_eviction():
    a = PageAllocator(6, 2)                  # 5 usable pages
    # request A: prompt pages (1,2) and (3,4), published as a chain
    pa, pb = a.alloc(), a.alloc()
    assert a.publish(pa, -1, (1, 2))
    assert a.publish(pb, pa, (3, 4))
    # a second publisher of the same key loses and keeps its page private
    pc = a.alloc()
    assert not a.publish(pc, -1, (1, 2))
    a.release(pc)                            # unpublished -> free list
    # lookup pins the whole chain; a diverging prompt stops at the match
    chain = a.lookup([1, 2, 3, 4, 9, 9], 3)
    assert chain == [pa, pb] and a.ref[pa] == 2 and a.ref[pb] == 2
    assert a.lookup([1, 2, 9, 9], 2) == [pa]     # second page diverges
    assert a.lookup([7, 7], 1) == []
    assert a.hits == 3 and a.misses == 3
    for p in (pa, pa, pb):                   # drop the lookup pins
        a.release(p)
    # publishers retire: ref-0 published pages park in the evictable LRU
    a.release(pa), a.release(pb)
    assert a.in_use == 0 and a.cached_pages == 2
    a.check()
    # exhaust the free list; the next allocs evict pa oldest-first, and
    # evicting pa CASCADES over pb (a child is unreachable without its
    # parent, and a recycled parent id must not match stale child keys)
    got = {a.alloc() for _ in range(5)}
    assert got == {1, 2, 3, 4, 5} and a.evictions == 2
    assert a.lookup([1, 2, 3, 4], 2) == []   # cache fully gone
    a.check()


def test_page_allocator_pin_revives_from_lru():
    a = PageAllocator(4, 2)
    p = a.alloc()
    assert a.publish(p, -1, (5, 6))
    a.release(p)
    assert a.cached_pages == 1
    assert a.lookup([5, 6, 7], 1) == [p]     # pin: LRU -> ref 1
    assert a.ref[p] == 1 and a.cached_pages == 0
    a.release(p)
    assert a.cached_pages == 1               # still published
    a.check()


def test_page_allocator_reset_rewinds_everything():
    a = PageAllocator(6, 2)
    p = a.alloc()
    a.publish(p, -1, (1, 2))
    a.alloc()
    a.reset()
    assert a.available == a.usable == 5 and a.in_use == 0
    assert a.cached_pages == 0 and a.hits == a.misses == 0
    assert a.lookup([1, 2], 1) == []
    a.check()


def test_page_allocator_fuzz_no_leaks_no_aliasing():
    """Randomized admit / publish / retire against the invariant audit:
    after every operation the free/evictable/live sets must partition
    the pool, no private page may be held by two requests, and draining
    all requests must return every reference."""
    rs = np.random.RandomState(0)
    ps = 4
    for trial in range(3):
        a = PageAllocator(num_pages=13, page_size=ps)
        # a small prompt universe so prefix collisions actually happen
        prompts = [tuple(rs.randint(0, 3, (ps * rs.randint(1, 4),)))
                   for _ in range(8)]
        live = []          # (chain pages, private pages, prompt, pub state)
        for _ in range(400):
            op = rs.rand()
            if op < 0.45:                                # admit
                prompt = prompts[rs.randint(len(prompts))]
                full = len(prompt) // ps
                need = full + 1                          # one decode page
                chain = a.lookup(prompt, full)
                if a.available < need - len(chain):
                    for p in reversed(chain):
                        a.release(p)
                else:
                    priv = [a.alloc() for _ in range(need - len(chain))]
                    live.append({"chain": chain, "priv": priv,
                                 "prompt": prompt, "pub": len(chain),
                                 "parent": chain[-1] if chain else -1})
            elif op < 0.65 and live:                     # publish one page
                st = live[rs.randint(len(live))]
                full = len(st["prompt"]) // ps
                k = st["pub"]
                if k < full:
                    page = (st["chain"] + st["priv"])[k]
                    tok = st["prompt"][k * ps:(k + 1) * ps]
                    if a.publish(page, st["parent"], tok):
                        st["pub"] = k + 1
                        st["parent"] = page
                    else:
                        st["pub"] = full     # lost the race: stop
            elif live:                                   # retire
                st = live.pop(rs.randint(len(live)))
                for p in st["chain"] + st["priv"]:
                    a.release(p)
            a.check()
            # no private page aliased between two live requests
            privs = [p for st in live for p in st["priv"]]
            assert len(privs) == len(set(privs))
            held = sum(len(st["chain"]) + len(st["priv"]) for st in live)
            assert a.in_use <= held          # shared pages count once
        while live:
            st = live.pop()
            for p in st["chain"] + st["priv"]:
                a.release(p)
            a.check()
        assert a.in_use == 0                 # no leaks after full drain


# ---------------------------------------------------------------------------
# chunk planning from a cached span + packing admission (no jax)
# ---------------------------------------------------------------------------

def test_plan_chunks_start_left_aligned_tail():
    # start at a cached span: windows begin there, never reach backwards
    assert plan_chunks(20, (4, 16), start=16) == [(16, 4)]
    # ragged tail LEFT-aligned with padding (right-aligning would rewrite
    # shared pages another request may be attending)
    assert plan_chunks(21, (4, 16), start=16) == [(16, 16)]
    assert plan_chunks(50, (4, 16), start=16) == [(16, 16), (32, 16),
                                                  (48, 4)]
    assert plan_chunks(16, (4, 16), start=16) == []
    with pytest.raises(ValueError, match="outside"):
        plan_chunks(8, (4, 16), start=9)
    for n in range(1, 60):
        for start in range(0, n + 1, 4):
            covered = set()
            for w, size in plan_chunks(n, (4, 16), start=start):
                assert w >= start            # never rewrites cached pages
                covered.update(range(w, w + size))
            assert covered.issuperset(range(start, n))


def test_pages_needed_and_packing_admission():
    ps = 4
    # span = prompt-1 prefill positions + max_new decode writes
    assert Scheduler.pages_needed(Request(0, [1] * 5, 4), ps) == 2
    assert Scheduler.pages_needed(Request(0, [1] * 5, 6), ps) == 3
    assert Scheduler.pages_needed(Request(0, [1], 1), ps) == 1
    a = PageAllocator(6, ps)                 # 5 usable
    s = Scheduler((4,), max_len=32, admit_lookahead=4)
    s.submit(Request(0, [1] * 5, 6))         # 3 pages
    [st0] = s.admit([0, 1], now=0.0, allocator=a)
    assert st0.req.id == 0 and a.in_use == 3
    s.submit(Request(1, [1] * 9, 8))         # 4 pages: does NOT fit
    s.submit(Request(2, [2] * 3, 4))         # 2 pages: fits
    admitted = s.admit([1], now=0.0, allocator=a)
    # packing: the short request behind the too-big head rides along
    assert [st.req.id for st in admitted] == [2]
    assert s.queue[0].id == 1                # FCFS head preserved
    # head fits again once the first request's pages release
    for st in (st0, admitted[0]):
        s.retire(st)
        for p in st.owned_pages:
            a.release(p)
    assert [st.req.id for st in s.admit([0, 1], now=0.0, allocator=a)] \
        == [1]
    a.check()


def test_admission_reserves_worst_case_and_rejects_when_full():
    ps = 4
    a = PageAllocator(5, ps)                 # 4 usable
    s = Scheduler((4,), max_len=32, admit_lookahead=2)
    s.submit(Request(0, [1] * 5, 6))         # 3 pages -> fits
    s.submit(Request(1, [1] * 5, 6))         # 3 pages -> must wait
    admitted = s.admit([0, 1], now=0.0, allocator=a)
    assert [st.req.id for st in admitted] == [0]
    assert admitted[0].page_table[:3] != [0, 0, 0]
    assert len(s.queue) == 1                 # no partial reservation
    assert a.in_use == 3                     # nothing leaked by the miss
    a.check()


# ---------------------------------------------------------------------------
# the paged Pallas kernel vs the gathered dense oracle
# ---------------------------------------------------------------------------

def _dense_ref(q, k, v, curs, k_scale=None, v_scale=None):
    if k_scale is not None:
        k = k.astype(jnp.float32) * k_scale[..., None]
        v = v.astype(jnp.float32) * v_scale[..., None]
    B, KV, L, D = k.shape
    H = q.shape[1]
    k = jnp.repeat(k, H // KV, axis=1)
    v = jnp.repeat(v, H // KV, axis=1)
    s = jnp.einsum("bhd,bhld->bhl", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (D ** 0.5)
    mask = jnp.arange(L)[None, None] <= curs[:, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhl,bhld->bhd", p, v.astype(jnp.float32))


def _scatter_pages(contig, pt, NP, ps):
    """[B, KV, L, *] logical rows -> [NP, KV, ps, *] pool via the page
    table, POISON in every pool slot no table entry maps (incl. trash)."""
    B, KV, L = contig.shape[:3]
    pool = np.full((NP, KV, ps) + contig.shape[3:], POISON,
                   contig.dtype if contig.dtype != np.int8 else np.float32)
    pool = pool.astype(contig.dtype)
    if contig.dtype == np.int8:
        pool[:] = 127
    for b in range(B):
        for j in range(L // ps):
            pool[pt[b, j]] = contig[b, :, j * ps:(j + 1) * ps]
    return pool


@pytest.mark.parametrize("H,KV", [(4, 4), (4, 2)])
@pytest.mark.parametrize("quantized", [False, True])
def test_paged_kernel_matches_dense(H, KV, quantized):
    """Per-row cursors at block starts/interiors/ends over a shuffled
    page table; beyond-cursor pool content is poisoned so a wrong page
    resolution or missing mask shows up as a huge error."""
    B, D, ps, nblk = 4, 16, 16, 4
    L = ps * nblk
    NP = B * nblk + 2                        # trash + one never-mapped
    curs = np.array([0, 17, 31, 63], np.int32)
    rs = np.random.RandomState(5)
    # distinct physical pages per logical block, shuffled across the pool
    perm = rs.permutation(np.arange(1, NP - 1)).reshape(B, nblk)
    pt = perm.astype(np.int32)
    q = jax.random.normal(jax.random.PRNGKey(0), (B, H, D), jnp.float32)
    k = rs.randn(B, KV, L, D).astype(np.float32)
    v = rs.randn(B, KV, L, D).astype(np.float32)
    dead = np.arange(L)[None, None, :, None] > curs[:, None, None, None]
    ks = vs = ksp = vsp = None
    if quantized:
        ks = np.maximum(np.abs(k).max(-1) / 127.0, 1e-8).astype(np.float32)
        vs = np.maximum(np.abs(v).max(-1) / 127.0, 1e-8).astype(np.float32)
        k = np.clip(np.round(k / ks[..., None]), -127, 127)
        v = np.clip(np.round(v / vs[..., None]), -127, 127)
        k = np.where(dead, 127, k).astype(np.int8)
        v = np.where(dead, 127, v).astype(np.int8)
        ks = np.where(dead[..., 0], POISON, ks)
        vs = np.where(dead[..., 0], POISON, vs)
        ksp = jnp.asarray(_scatter_pages(ks[..., None], pt, NP, ps)[..., 0])
        vsp = jnp.asarray(_scatter_pages(vs[..., None], pt, NP, ps)[..., 0])
    else:
        k = np.where(dead, POISON, k)
        v = np.where(dead, POISON, v)
    kp = jnp.asarray(_scatter_pages(k, pt, NP, ps))
    vp = jnp.asarray(_scatter_pages(v, pt, NP, ps))
    ref = _dense_ref(q, jnp.asarray(k), jnp.asarray(v),
                     jnp.asarray(curs),
                     None if ks is None else jnp.asarray(ks),
                     None if vs is None else jnp.asarray(vs))
    out = paged_decode_attention(q, kp, vp, jnp.asarray(curs),
                                 jnp.asarray(pt), k_scale=ksp,
                                 v_scale=vsp, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_paged_kernel_shared_pages_between_rows():
    """Two rows whose tables alias the SAME physical prefix page (the
    prefix-cache layout) read identical K/V through it."""
    B, H, KV, D, ps, nblk = 2, 4, 2, 16, 16, 2
    NP = 4
    pt = np.array([[1, 2], [1, 3]], np.int32)    # page 1 shared
    curs = np.array([ps + 3, ps + 7], np.int32)
    rs = np.random.RandomState(9)
    pool_k = rs.randn(NP, KV, ps, D).astype(np.float32)
    pool_v = rs.randn(NP, KV, ps, D).astype(np.float32)
    q = jax.random.normal(jax.random.PRNGKey(1), (B, H, D), jnp.float32)
    # gather the logical view per row, then dense-reference it
    gk = np.stack([np.concatenate([pool_k[p] for p in pt[b]], axis=1)
                   for b in range(B)])
    gv = np.stack([np.concatenate([pool_v[p] for p in pt[b]], axis=1)
                   for b in range(B)])
    ref = _dense_ref(q, jnp.asarray(gk), jnp.asarray(gv),
                     jnp.asarray(curs))
    out = paged_decode_attention(q, jnp.asarray(pool_k),
                                 jnp.asarray(pool_v), jnp.asarray(curs),
                                 jnp.asarray(pt), interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


# ---------------------------------------------------------------------------
# the paged engine vs the contiguous oracle
# ---------------------------------------------------------------------------

def _setup(decode_kernel=False, kv_cache_dtype=None, slots=4,
           page_size=8, num_pages=None, max_len=64):
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=max_len,
                      kv_cache_dtype=kv_cache_dtype)
    model = CausalLM(cfg)
    probe = jnp.zeros((1, 4), jnp.int32)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), probe))["params"]
    contiguous = ServingEngine(model, params, EngineConfig(
        slots=slots, chunk_buckets=(4, 8), decode_kernel=decode_kernel))
    paged = ServingEngine(model, params, EngineConfig(
        slots=slots, chunk_buckets=(4, 8), decode_kernel=decode_kernel,
        paged=True, page_size=page_size, num_pages=num_pages))
    return contiguous, paged


def _mixed_trace(n=8, seed=7, eos=None):
    rs = np.random.RandomState(seed)
    lens = [(1, 6), (3, 9), (9, 4), (14, 7), (5, 5), (7, 8), (12, 6),
            (2, 7)]
    return [Request(i, list(rs.randint(0, 64, (p,))), max_new_tokens=m,
                    eos_id=eos)
            for i, (p, m) in enumerate(lens[:n])]


@pytest.mark.parametrize("decode_kernel", [False, True])
def test_paged_engine_token_exact_vs_contiguous(decode_kernel):
    """The acceptance gate: greedy decode through the paged cache is
    token-for-token identical to the contiguous engine on the same
    trace — mixed prompt lengths, more requests than slots (slot AND
    page reuse across retire/admit), dense and kernel paths."""
    contiguous, paged = _setup(decode_kernel)
    trace = _mixed_trace()
    want = contiguous.run(trace)
    got = paged.run(trace)
    for r in trace:
        assert got[r.id].tokens == want[r.id].tokens, \
            f"request {r.id} diverged"
        assert got[r.id].finish_reason == want[r.id].finish_reason
    alloc = paged.page_allocator
    alloc.check()
    assert alloc.in_use == 0                 # every page released
    counts = paged.compile_counts()
    assert counts["step"] == 1 and counts["prefill"] <= 2


def test_paged_engine_int8_cache_token_exact():
    """The quantized cache pages ([NP, KV, ps] scale planes) through the
    same oracle: int8 contiguous vs int8 paged, dense path."""
    contiguous, paged = _setup(kv_cache_dtype="int8")
    trace = _mixed_trace(n=5)
    want = contiguous.run(trace)
    got = paged.run(trace)
    for r in trace:
        assert got[r.id].tokens == want[r.id].tokens, \
            f"request {r.id} diverged"


def test_paged_engine_eos_retirement_reuses_pages():
    """EOS mid-flight: retired requests release pages that later
    arrivals re-allocate; tokens still match the contiguous engine."""
    contiguous, paged = _setup()
    probe = contiguous.run(_mixed_trace(n=1))
    eos = probe[0].tokens[2]
    contiguous.reset()
    trace = _mixed_trace(eos=eos)            # 8 requests over 4 slots
    want = contiguous.run(trace)
    got = paged.run(trace)
    assert any(r.finish_reason == "eos" for r in got.values())
    for r in trace:
        assert got[r.id].tokens == want[r.id].tokens
    assert paged.page_allocator.in_use == 0


@pytest.mark.parametrize("decode_kernel", [False, True])
def test_prefix_hit_token_exact_and_skips_prefill(decode_kernel):
    """A request sharing a cached prompt prefix admits with
    cached_tokens > 0, runs FEWER prefill chunks, produces the exact
    contiguous tokens, and reaches its first token faster from admission
    (the queue-independent TTFT the bench reports)."""
    contiguous, paged = _setup(decode_kernel)
    rs = np.random.RandomState(3)
    shared = list(rs.randint(0, 64, (40,)))      # 5 full pages of 8
    cold = Request(0, shared + list(rs.randint(0, 64, (3,))), 6)
    hot = Request(1, shared + list(rs.randint(0, 64, (3,))), 6)
    want0 = contiguous.run([cold])
    want1 = contiguous.run([hot])
    got0 = paged.run([cold])                 # publishes the 5 pages
    got1 = paged.run([hot])                  # pins them
    assert got0[0].tokens == want0[0].tokens
    assert got1[1].tokens == want1[1].tokens
    assert got0[0].cached_tokens == 0
    assert got1[1].cached_tokens == 40
    # the hit skipped the shared prefill: first token comes faster from
    # admission (5 chunk programs of work it never ran)
    t_cold = got0[0].token_times[0] - got0[0].admitted_at
    t_hot = got1[1].token_times[0] - got1[1].admitted_at
    assert t_hot < t_cold
    alloc = paged.page_allocator
    assert alloc.hits == 5 and alloc.cached_pages == 5
    alloc.check()


def test_prefix_divergence_is_copy_on_write():
    """Two prompts equal through page 2 then diverging INSIDE page 3:
    the hit stops at the divergence page, which stays private — the
    original's cached page is untouched and both match the oracle."""
    contiguous, paged = _setup()
    rs = np.random.RandomState(13)
    head = list(rs.randint(0, 64, (16,)))        # 2 full pages of 8
    a = Request(0, head + list(rs.randint(0, 64, (7,))), 5)
    b = Request(1, head + list(rs.randint(0, 64, (7,))), 5)
    want_a = contiguous.run([a])
    want_b = contiguous.run([b])
    got_a = paged.run([a])
    got_b = paged.run([b])
    assert got_a[0].tokens == want_a[0].tokens
    assert got_b[1].tokens == want_b[1].tokens
    assert got_b[1].cached_tokens == 16          # only the shared pages
    # replaying A must still hit ITS chain exactly (page 3 not clobbered)
    want_a2 = contiguous.run([a])
    got_a2 = paged.run([a])
    assert got_a2[0].tokens == want_a2[0].tokens
    paged.page_allocator.check()


def test_paged_capacity_beats_contiguous_at_equal_bytes():
    """The tentpole's capacity claim: under the SAME cache byte budget
    (2 contiguous rows of max_len=64 vs 16+1 pages of 8), the paged
    engine sustains strictly more concurrent requests because short
    requests reserve their actual worst case, not a whole row."""
    budget_rows = 2
    contiguous, paged = _setup(
        slots=budget_rows, page_size=8,
        num_pages=budget_rows * (64 // 8) + 1)   # byte parity + trash
    # 6 short requests: each needs (6-2+6)//8+1 = 2 pages — the pool
    # fits 6 concurrently (12 of 16 pages), contiguous caps at 2 rows
    reqs = [Request(i, [int(t) for t in
                        np.random.RandomState(i).randint(0, 64, (6,))],
                    max_new_tokens=6) for i in range(6)]
    want = contiguous.run(reqs)
    assert contiguous.occupancy_peak == budget_rows
    # a paged engine with MORE slots over the SAME pool bytes
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=64)
    model = CausalLM(cfg)
    probe = jnp.zeros((1, 4), jnp.int32)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), probe))["params"]
    paged_wide = ServingEngine(model, params, EngineConfig(
        slots=6, chunk_buckets=(4, 8), paged=True, page_size=8,
        num_pages=budget_rows * (64 // 8) + 1))
    got = paged_wide.run(reqs)
    for r in reqs:
        assert got[r.id].tokens == want[r.id].tokens
    assert paged_wide.occupancy_peak > budget_rows
    assert paged_wide.pages_in_use_peak <= budget_rows * (64 // 8)


def test_paged_engine_rejects_unservable_request():
    """A request whose worst-case span exceeds the whole pool can never
    admit — run() rejects it up front instead of livelocking."""
    _, paged = _setup(num_pages=4, page_size=8)  # 3 usable pages
    with pytest.raises(ValueError, match="KV pages"):
        paged.run([Request(0, [1] * 20, max_new_tokens=20)])
