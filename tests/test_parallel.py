"""Parallelism-strategy tests on the 8-virtual-device CPU mesh.

The reference tests multi-node behavior declaratively (SURVEY.md §4); we own
a data plane, so every strategy is verified numerically against its dense /
sequential reference: TP+FSDP (sharded == replicated forward), SP (ring ==
dense attention), EP (sharded MoE == single-device MoE), PP (pipeline ==
sequential stages) — forward AND backward.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import meta
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_operator_tpu.models.transformer import (
    CausalLM, dense_attention, gpt2_config)
from mpi_operator_tpu.parallel import (
    MeshConfig, MoeMlp, make_mesh, pipeline_apply, ring_attention,
    shard_init, stack_stage_params)
from mpi_operator_tpu.utils.compat import HAS_VMA

# The pipeline's partial-manual shard_map (pp manual, tp/ep auto) +
# lax.axis_index lowers to a PartitionId instruction that this jax
# vintage's SPMD partitioner rejects outright ("UNIMPLEMENTED:
# PartitionId instruction is not supported for SPMD partitioning") —
# seed-era failures, triaged in ROADMAP "Open items". The probe is the
# same one utils/compat.py keys its shims on: the modern (vma-style)
# shard_map partitions these fine, so a jax upgrade re-enables them
# automatically instead of leaving a stale skip behind.
needs_partial_manual_spmd = pytest.mark.skipif(
    not HAS_VMA,
    reason="partial-manual shard_map + lax.axis_index lowers to a "
           "PartitionId instruction this XLA's SPMD partitioner rejects "
           "(ROADMAP Open items)")


# ---------------------------------------------------------------------------
# tensor parallel + fsdp
# ---------------------------------------------------------------------------

class TestTensorParallel:
    def test_sharded_forward_matches_replicated(self):
        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=512, max_len=64)
        model = CausalLM(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0, 512)
        vs_ref = meta.unbox(model.init(jax.random.PRNGKey(7), toks))
        ref = model.apply(vs_ref, toks)

        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        vs, shardings = shard_init(model, mesh, jax.random.PRNGKey(7), toks)
        toks_sh = jax.device_put(
            toks, NamedSharding(mesh, P(("dcn", "dp", "fsdp"))))
        out = jax.jit(model.apply)(vs, toks_sh)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5)

    def test_params_actually_sharded(self):
        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=512, max_len=64)
        model = CausalLM(cfg)
        toks = jnp.zeros((2, 16), jnp.int32)
        mesh = make_mesh(MeshConfig(tp=8))
        vs, shardings = shard_init(model, mesh, jax.random.PRNGKey(0), toks)
        # the FFN in-projection must be tp-sharded on its mlp dim
        k = vs["params"]["backbone"]["block_0"]["mlp"]["fc_in"]["kernel"]
        spec = k.sharding.spec
        assert "tp" in jax.tree.leaves(tuple(spec)), spec
        # local shard is 1/8th of the full mlp dim
        assert k.addressable_shards[0].data.shape[-1] == k.shape[-1] // 8


# ---------------------------------------------------------------------------
# sequence parallel (ring attention)
# ---------------------------------------------------------------------------

class TestRingAttention:
    @pytest.mark.parametrize("impl", ["dense", "flash"])
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal, impl):
        mesh = make_mesh(MeshConfig(dp=2, sp=4))
        B, S, H, D = 4, 64, 2, 16
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, S, H, D))
                   for i in range(3))
        ref = dense_attention(q, k, v, causal=causal, dtype=jnp.float32)
        out = ring_attention(q, k, v, mesh, causal=causal, impl=impl)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=1e-5)

    @pytest.mark.parametrize("impl", ["dense", "flash"])
    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_dense(self, causal, impl):
        mesh = make_mesh(MeshConfig(sp=8))
        B, S, H, D = 2, 32, 2, 8
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, S, H, D))
                   for i in range(3))

        def lr(q, k, v):
            return (ring_attention(q, k, v, mesh, causal=causal,
                                   impl=impl) ** 2).sum()

        def ld(q, k, v):
            return (dense_attention(q, k, v, causal=causal,
                                    dtype=jnp.float32) ** 2).sum()

        g1 = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)


# ---------------------------------------------------------------------------
# expert parallel (MoE)
# ---------------------------------------------------------------------------

class TestMoE:
    def _model_and_input(self):
        m = MoeMlp(num_experts=4, embed_dim=32, mlp_dim=64, top_k=2,
                   capacity_factor=2.0, dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32))
        vs = meta.unbox(m.init(jax.random.PRNGKey(1), x))
        return m, x, vs

    def test_forward_and_aux(self):
        m, x, vs = self._model_and_input()
        out, aux = m.apply(vs, x)
        assert out.shape == x.shape
        assert float(aux) >= 1.0 - 1e-5     # aux >= 1 at/above uniform load

    def test_ep_sharded_matches_dense(self):
        m, x, vs = self._model_and_input()
        out, _ = m.apply(vs, x)
        mesh = make_mesh(MeshConfig(dp=2, ep=4))
        from mpi_operator_tpu.parallel.sharding import param_shardings
        abstract = jax.eval_shape(lambda r: m.init(r, x),
                                  jax.random.PRNGKey(1))
        sh = param_shardings(mesh, abstract)
        out_sh = jax.tree.unflatten(
            jax.tree.structure(meta.unbox(abstract)), jax.tree.leaves(sh))
        vs_sharded = jax.jit(lambda v: v, out_shardings=out_sh)(vs)
        xs = jax.device_put(x, NamedSharding(mesh, P(("dp",))))
        out2, _ = jax.jit(m.apply)(vs_sharded, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                                   atol=1e-5)

    def test_capacity_drops_tokens(self):
        """With capacity 1 token/expert, most tokens are dropped — output
        stays finite and partially zero."""
        m = MoeMlp(num_experts=2, embed_dim=8, mlp_dim=16, top_k=1,
                   capacity_factor=0.01, dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 8))
        vs = meta.unbox(m.init(jax.random.PRNGKey(1), x))
        out, _ = m.apply(vs, x)
        assert bool(jnp.isfinite(out).all())
        row_norms = jnp.abs(out[0]).sum(-1)
        assert int((row_norms == 0).sum()) >= 16   # dropped rows contribute 0

    def test_grads_finite(self):
        m, x, vs = self._model_and_input()

        def loss(p):
            out, aux = m.apply(p, x)
            return (out ** 2).mean() + 0.01 * aux

        grads = jax.grad(loss)(vs)
        for g in jax.tree.leaves(grads):
            assert bool(jnp.isfinite(g).all())


# ---------------------------------------------------------------------------
# pipeline parallel
# ---------------------------------------------------------------------------

class TestPipeline:
    def _setup(self):
        mesh = make_mesh(MeshConfig(dp=2, pp=4))
        E = 16
        per_stage = [
            {"w": jax.random.normal(jax.random.PRNGKey(i), (E, E))
             / np.sqrt(E), "b": jnp.zeros((E,))} for i in range(4)]
        stacked = stack_stage_params(per_stage)

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"][0] + p["b"][0])

        x = jax.random.normal(jax.random.PRNGKey(99), (8, 4, E))
        return mesh, per_stage, stacked, stage_fn, x

    def _sequential(self, per_stage, x):
        h = x
        for p in per_stage:
            h = jnp.tanh(h @ p["w"] + p["b"])
        return h

    def test_forward_matches_sequential(self):
        mesh, per_stage, stacked, stage_fn, x = self._setup()
        out = pipeline_apply(stage_fn, stacked, x, mesh, num_microbatches=8)
        ref = self._sequential(per_stage, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)

    def test_backward_matches_sequential(self):
        mesh, per_stage, stacked, stage_fn, x = self._setup()

        def loss_pipe(params):
            return (pipeline_apply(stage_fn, params, x, mesh,
                                   num_microbatches=8) ** 2).sum()

        def loss_seq(per):
            return (self._sequential(per, x) ** 2).sum()

        g1 = jax.grad(loss_pipe)(stacked)
        g2 = stack_stage_params(jax.grad(loss_seq)(per_stage))
        np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(g1["b"]), np.asarray(g2["b"]),
                                   atol=1e-5)


class TestPipelineLM:
    """VERDICT #6: the pipeline must carry an actual transformer, not a
    toy layer — loss and grads of the stage-sliced CausalLM must match the
    unpiped model on identical parameters."""

    def _setup(self):
        from mpi_operator_tpu.parallel import pipeline_lm_loss, stack_lm_params
        from mpi_operator_tpu.train.lm_trainer import lm_loss

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=256, max_len=32)      # 2 layers
        model = CausalLM(cfg)
        B, S, M = 8, 16, 4
        key = jax.random.PRNGKey(3)
        toks = jax.random.randint(key, (B, S + 1), 0, cfg.vocab_size)
        toks, tgts = toks[:, :-1], toks[:, 1:]
        vs = meta.unbox(model.init(jax.random.PRNGKey(7), toks))
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        pp_params = stack_lm_params(vs["params"], cfg.num_layers)
        mb = (toks.reshape(M, B // M, S), tgts.reshape(M, B // M, S))
        return (cfg, model, vs, toks, tgts, mesh, pp_params, mb, M,
                pipeline_lm_loss, stack_lm_params, lm_loss)

    def test_loss_matches_unpiped(self):
        (cfg, model, vs, toks, tgts, mesh, pp_params, (tk, tg), M,
         pipeline_lm_loss, _, lm_loss) = self._setup()
        ref = lm_loss(model.apply(vs, toks), tgts)
        out = jax.jit(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M))(pp_params)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=1e-5)

    def test_grads_match_unpiped(self):
        (cfg, model, vs, toks, tgts, mesh, pp_params, (tk, tg), M,
         pipeline_lm_loss, stack_lm_params, lm_loss) = self._setup()

        g_pipe = jax.jit(jax.grad(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M)))(pp_params)
        g_ref = jax.grad(lambda p: lm_loss(
            model.apply({"params": p}, toks), tgts))(vs["params"])
        g_ref = stack_lm_params(g_ref, cfg.num_layers)
        flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
        flat_r = jax.tree.leaves(g_ref)
        assert len(flat_p) == len(flat_r)
        for (path, a), b in zip(flat_p, flat_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4,
                err_msg=jax.tree_util.keystr(path))

    def test_dp_sharded_stream_matches_unpiped(self):
        """pp×dp with the microbatch dim actually SHARDED over dp (mb
        divisible by the data degree): each dp rank pipelines its own
        slice and the psum spans pp+dp — loss and grads must still match
        the unpiped model exactly."""
        from mpi_operator_tpu.parallel import pipeline_lm_loss, stack_lm_params
        from mpi_operator_tpu.train.lm_trainer import lm_loss

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=256, max_len=32)
        model = CausalLM(cfg)
        B, S, M = 16, 16, 4                   # mb=4 divides dp=4 → sharded
        toks = jax.random.randint(jax.random.PRNGKey(3), (B, S + 1), 0,
                                  cfg.vocab_size)
        toks, tgts = toks[:, :-1], toks[:, 1:]
        vs = meta.unbox(model.init(jax.random.PRNGKey(7), toks))
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        pp_params = stack_lm_params(vs["params"], cfg.num_layers)
        tk, tg = toks.reshape(M, B // M, S), tgts.reshape(M, B // M, S)

        ref = lm_loss(model.apply(vs, toks), tgts)
        out = jax.jit(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M))(pp_params)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=1e-5)
        g_pipe = jax.jit(jax.grad(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M)))(pp_params)
        g_ref = stack_lm_params(
            jax.grad(lambda p: lm_loss(
                model.apply({"params": p}, toks), tgts))(vs["params"]),
            cfg.num_layers)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    def test_pp_sp_ring_stream_matches_unpiped(self):
        """pp×sp composition: the stream's sequence dim sharded over sp,
        each stage tick ringing its attention over the sp neighbors
        (cfg.attention='ring' + positions offset per shard) — loss AND
        grads must match the unpiped dense model on identical params."""
        import dataclasses

        from mpi_operator_tpu.parallel import pipeline_lm_loss, stack_lm_params
        from mpi_operator_tpu.train.lm_trainer import lm_loss

        cfg_ring = gpt2_config("test", attention="ring", dtype=jnp.float32,
                               vocab_size=256, max_len=32)
        cfg_dense = dataclasses.replace(cfg_ring, attention="dense")
        model = CausalLM(cfg_dense)
        B, S, M = 8, 16, 4
        toks = jax.random.randint(jax.random.PRNGKey(3), (B, S + 1), 0,
                                  cfg_ring.vocab_size)
        toks, tgts = toks[:, :-1], toks[:, 1:]
        vs = meta.unbox(model.init(jax.random.PRNGKey(7), toks))
        mesh = make_mesh(MeshConfig(pp=2, sp=2, dp=2))
        pp_params = stack_lm_params(vs["params"], cfg_ring.num_layers)
        tk, tg = toks.reshape(M, B // M, S), tgts.reshape(M, B // M, S)

        ref = lm_loss(model.apply(vs, toks), tgts)
        out = jax.jit(lambda p: pipeline_lm_loss(
            cfg_ring, p, tk, tg, mesh, M))(pp_params)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5)
        g_pipe = jax.jit(jax.grad(lambda p: pipeline_lm_loss(
            cfg_ring, p, tk, tg, mesh, M)))(pp_params)
        g_ref = stack_lm_params(
            jax.grad(lambda p: lm_loss(
                model.apply({"params": p}, toks), tgts))(vs["params"]),
            cfg_ring.num_layers)
        flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
        for (path, a), b in zip(flat_p, jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-4,
                err_msg=jax.tree_util.keystr(path))

    def test_pp_sp_rejects_non_ring_attention(self):
        """A dense/flash stage body under sp would attend within its own
        S/sp shard only — silently truncated context. Rejected loudly."""
        from mpi_operator_tpu.parallel import pipeline_lm_loss, stack_lm_params

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=256, max_len=32)
        model = CausalLM(cfg)
        vs = meta.unbox(model.init(jax.random.PRNGKey(0),
                                   jnp.zeros((2, 16), jnp.int32)))
        pp_params = stack_lm_params(vs["params"], cfg.num_layers)
        mesh = make_mesh(MeshConfig(pp=2, sp=2, dp=2))
        tk = jnp.zeros((4, 2, 16), jnp.int32)
        with pytest.raises(ValueError, match="ring"):
            pipeline_lm_loss(cfg, pp_params, tk, tk, mesh, 4)

    def test_masked_lm_pipeline_matches_unpiped(self):
        """The pipelined MaskedLM (BERT family): mask stream riding the
        relays, MLM transform head on the last stage, dynamic mask-count
        divisor — loss AND grads must match the unpiped MaskedLM +
        lm_loss(mask) on identical params."""
        from mpi_operator_tpu.models.transformer import (MaskedLM,
                                                         bert_config)
        from mpi_operator_tpu.parallel import (pipeline_mlm_loss,
                                               stack_mlm_params)
        from mpi_operator_tpu.train.lm_trainer import lm_loss

        cfg = bert_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=256, max_len=32)      # 2 layers
        model = MaskedLM(cfg)
        B, S, M = 8, 16, 4
        key = jax.random.PRNGKey(3)
        orig = jax.random.randint(key, (B, S), 0, cfg.vocab_size)
        mask = (jax.random.uniform(jax.random.PRNGKey(5), (B, S))
                < 0.25).astype(jnp.float32)
        toks = jnp.where(mask > 0, cfg.vocab_size - 1, orig)
        vs = meta.unbox(model.init(jax.random.PRNGKey(7), toks))
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        pp_params = stack_mlm_params(vs["params"], cfg.num_layers)
        tk = toks.reshape(M, B // M, S)
        tg = orig.reshape(M, B // M, S)
        mk = mask.reshape(M, B // M, S)

        ref = lm_loss(model.apply(vs, toks), orig, mask)
        out = jax.jit(lambda p: pipeline_mlm_loss(
            cfg, p, tk, tg, mk, mesh, M))(pp_params)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5)
        g_pipe = jax.jit(jax.grad(lambda p: pipeline_mlm_loss(
            cfg, p, tk, tg, mk, mesh, M)))(pp_params)
        g_ref = stack_mlm_params(
            jax.grad(lambda p: lm_loss(
                model.apply({"params": p}, toks), orig, mask))(
                vs["params"]),
            cfg.num_layers)
        flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
        flat_r = jax.tree_util.tree_flatten_with_path(g_ref)[0]
        assert [p for p, _ in flat_p] == [p for p, _ in flat_r]
        for (path, a), (_, b) in zip(flat_p, flat_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-4,
                err_msg=jax.tree_util.keystr(path))

    def test_pp_fused_xent_matches_unfused(self):
        """--fused-xent with --pp (VERDICT r04 next #7): the chunked
        tied-head loss on the last stage must equal the unfused pp loss
        and grads exactly — GPipe and 1F1B."""
        from mpi_operator_tpu.parallel import pipeline_lm_loss, stack_lm_params
        from mpi_operator_tpu.parallel.pipeline_1f1b import (
            pipeline_lm_1f1b_grads)

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=256, max_len=32)
        model = CausalLM(cfg)
        B, S, M = 8, 16, 4
        toks = jax.random.randint(jax.random.PRNGKey(3), (B, S + 1), 0,
                                  cfg.vocab_size)
        toks, tgts = toks[:, :-1], toks[:, 1:]
        vs = meta.unbox(model.init(jax.random.PRNGKey(7), toks))
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        pp_params = stack_lm_params(vs["params"], cfg.num_layers)
        tk, tg = toks.reshape(M, B // M, S), tgts.reshape(M, B // M, S)

        l0, g0 = jax.jit(jax.value_and_grad(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M)))(pp_params)
        l1, g1 = jax.jit(jax.value_and_grad(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M, fused_xent=True)))(pp_params)
        np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                                   atol=2e-5)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)
        lf, gf = jax.jit(lambda p: pipeline_lm_1f1b_grads(
            cfg, p, tk, tg, mesh, M, fused_xent=True))(pp_params)
        np.testing.assert_allclose(np.asarray(l0), np.asarray(lf),
                                   atol=2e-5)
        for a, b in zip(jax.tree.leaves(g0["blocks"]),
                        jax.tree.leaves(gf["blocks"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    def test_masked_pp_sp_ring_matches_unpiped(self):
        """pp×sp for the MASKED (BERT) pipeline (advisor r04): the
        bidirectional ring-attention stage body under the pipeline with
        the sp-sharded mask stream — loss AND grads must match the
        unpiped dense MaskedLM on identical params (the causal pp×sp and
        masked pp×dp combinations each had this pin; the composition now
        does too)."""
        import dataclasses

        from mpi_operator_tpu.models.transformer import (MaskedLM,
                                                         bert_config)
        from mpi_operator_tpu.parallel import (pipeline_mlm_loss,
                                               stack_mlm_params)
        from mpi_operator_tpu.train.lm_trainer import lm_loss

        cfg_ring = bert_config("test", attention="ring", dtype=jnp.float32,
                               vocab_size=256, max_len=32)
        model = MaskedLM(dataclasses.replace(cfg_ring, attention="dense"))
        B, S, M = 8, 32, 4
        orig = jax.random.randint(jax.random.PRNGKey(3), (B, S), 0,
                                  cfg_ring.vocab_size)
        mask = (jax.random.uniform(jax.random.PRNGKey(5), (B, S))
                < 0.25).astype(jnp.float32)
        toks = jnp.where(mask > 0, cfg_ring.vocab_size - 1, orig)
        vs = meta.unbox(model.init(jax.random.PRNGKey(7), toks))
        mesh = make_mesh(MeshConfig(pp=2, sp=2, dp=2))
        pp_params = stack_mlm_params(vs["params"], cfg_ring.num_layers)
        tk = toks.reshape(M, B // M, S)
        tg = orig.reshape(M, B // M, S)
        mk = mask.reshape(M, B // M, S)

        ref = lm_loss(model.apply(vs, toks), orig, mask)
        out = jax.jit(lambda p: pipeline_mlm_loss(
            cfg_ring, p, tk, tg, mk, mesh, M))(pp_params)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5)
        g_pipe = jax.jit(jax.grad(lambda p: pipeline_mlm_loss(
            cfg_ring, p, tk, tg, mk, mesh, M)))(pp_params)
        g_ref = stack_mlm_params(
            jax.grad(lambda p: lm_loss(
                model.apply({"params": p}, toks), orig, mask))(
                vs["params"]),
            cfg_ring.num_layers)
        flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
        flat_r = jax.tree_util.tree_flatten_with_path(g_ref)[0]
        assert [p for p, _ in flat_p] == [p for p, _ in flat_r]
        for (path, a), (_, b) in zip(flat_p, flat_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-4,
                err_msg=jax.tree_util.keystr(path))

    def _moe_setup(self, dropless):
        """4-layer GPT-2 test config with MoE every 2nd block (blocks 1,3)
        — pp=2 stages each own one (dense, MoE) period."""
        from mpi_operator_tpu.parallel import (pipeline_lm_loss,
                                               stack_lm_params)
        from mpi_operator_tpu.train.lm_trainer import lm_loss

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=256, max_len=32, num_layers=4,
                          num_experts=4, moe_every=2,
                          moe_dropless=dropless)
        model = CausalLM(cfg)
        B, S, M = 8, 16, 4
        toks = jax.random.randint(jax.random.PRNGKey(3), (B, S + 1), 0,
                                  cfg.vocab_size)
        toks, tgts = toks[:, :-1], toks[:, 1:]
        vs = meta.unbox(model.init(jax.random.PRNGKey(7), toks))
        pp_params = stack_lm_params(vs["params"], cfg.num_layers,
                                    num_experts=cfg.num_experts,
                                    moe_every=cfg.moe_every)
        assert "moe" in pp_params
        tk, tg = toks.reshape(M, B // M, S), tgts.reshape(M, B // M, S)

        def oracle(params):
            # the honest MoE oracle is MICROBATCH-wise unpiped
            # application: capacity budgets and router means are per
            # router application (the GShard granularity), which for the
            # pipeline means per microbatch — identical token sets, so
            # loss AND grads must match exactly
            losses, auxs = [], []
            for m in range(M):
                logits, interm = model.apply(
                    {"params": params}, tk[m], mutable=["intermediates"])
                losses.append(lm_loss(logits, tg[m]))
                auxs.append(sum(
                    jnp.asarray(a).mean()
                    for a in jax.tree.leaves(interm["intermediates"])))
            return (sum(losses) / M) + 0.01 * (sum(auxs) / M)

        return (cfg, model, vs, pp_params, tk, tg, M, oracle,
                pipeline_lm_loss, stack_lm_params)

    @needs_partial_manual_spmd
    @pytest.mark.parametrize("dropless", [False, True])
    def test_pp_moe_matches_microbatched_unpiped(self, dropless):
        """pp×ep MoE (VERDICT r04 next #2): stage bodies scan (dense, MoE)
        periods with the expert dim sharded over ep as a GSPMD auto axis;
        loss (incl. the load-balance aux term at LMTrainer's weight) and
        grads must match microbatch-wise unpiped application exactly —
        capacity dispatch AND dropless mode."""
        (cfg, model, vs, pp_params, tk, tg, M, oracle,
         pipeline_lm_loss, stack_lm_params) = self._moe_setup(dropless)
        # dp=1: capacity budgets + router means are per router
        # application, so the oracle must see the same token sets the
        # stages do — a manual dp axis would halve them (documented
        # divergence, exercised in test_pp_moe_dp_sharded_runs)
        mesh = make_mesh(MeshConfig(pp=2, ep=4))

        ref = oracle(vs["params"])
        out, metrics = jax.jit(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M, with_moe_metrics=True))(pp_params)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5)
        # drop-rate observability rides the schedule (VERDICT: preserved)
        assert float(metrics["moe_drop_rate"]) >= 0.0
        if dropless:
            assert float(metrics["moe_drop_rate"]) == 0.0

        g_pipe = jax.jit(jax.grad(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M)))(pp_params)
        g_ref = stack_lm_params(jax.grad(oracle)(vs["params"]),
                                cfg.num_layers,
                                num_experts=cfg.num_experts,
                                moe_every=cfg.moe_every)
        flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
        flat_r = jax.tree_util.tree_flatten_with_path(g_ref)[0]
        assert [p for p, _ in flat_p] == [p for p, _ in flat_r]
        for (path, a), (_, b) in zip(flat_p, flat_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-4,
                err_msg=jax.tree_util.keystr(path))

    @needs_partial_manual_spmd
    def test_pp_moe_dp_sharded_runs(self):
        """pp×dp×ep MoE: with the microbatch dim manually dp-sharded each
        dp rank routes its own token slice (per-shard capacity budgets —
        the documented at-scale semantics, NOT full-microbatch parity).
        Pins: finite loss, drop rate observable, dropless drops == 0."""
        (cfg, model, vs, pp_params, tk, tg, M, _oracle,
         pipeline_lm_loss, _) = self._moe_setup(dropless=True)
        mesh = make_mesh(MeshConfig(pp=2, dp=2, ep=2))
        out, metrics = jax.jit(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M, with_moe_metrics=True))(pp_params)
        assert np.isfinite(float(out))
        assert float(metrics["moe_drop_rate"]) == 0.0
        g = jax.jit(jax.grad(lambda p: pipeline_lm_loss(
            cfg, p, tk, tg, mesh, M)))(pp_params)
        assert all(np.all(np.isfinite(np.asarray(x)))
                   for x in jax.tree.leaves(g))

    @needs_partial_manual_spmd
    def test_pp_moe_trainer_end_to_end(self):
        """PipelineLMTrainer with a MoE config: init → train steps →
        loss decreases trend not required, but steps run, the drop rate
        lands in benchmark metrics, and 1F1B/misaligned layouts are
        rejected loudly."""
        from mpi_operator_tpu.train.lm_trainer import LMTrainerConfig
        from mpi_operator_tpu.train.pp_trainer import PipelineLMTrainer

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=128, max_len=16, num_layers=4,
                          num_experts=4, moe_every=2)
        mesh = make_mesh(MeshConfig(pp=2, dp=2, ep=2))
        tcfg = LMTrainerConfig(global_batch_size=16, seq_len=16,
                               warmup_steps=1)
        trainer = PipelineLMTrainer(cfg, mesh, tcfg, num_microbatches=4)
        state = trainer.init_state(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (16, 17), 0, 128)
        batch = trainer.microbatch(toks[:, :-1], toks[:, 1:])
        state, m = trainer.train_step(state, *batch)
        assert np.isfinite(float(m["loss"]))
        assert "moe_drop_rate" in m

        class Rep:
            def __iter__(self):
                return iter([batch] * 8)

        state, bm = trainer.benchmark(state, Rep(), num_steps=2,
                                      warmup_steps=1, log=lambda s: None)
        assert "moe_drop_rate" in bm
        # eval excludes the aux term: val_loss <= train loss at same params
        ev = trainer.evaluate(state, Rep(), num_batches=1)
        assert np.isfinite(ev["val_loss"])

        with pytest.raises(ValueError, match="gpipe"):
            PipelineLMTrainer(cfg, mesh, tcfg, num_microbatches=4,
                              schedule="1f1b")
        bad = gpt2_config("test", attention="dense", num_layers=2,
                          num_experts=4, moe_every=2)
        with pytest.raises(ValueError, match="whole dense\\+MoE periods"):
            PipelineLMTrainer(bad, mesh, tcfg, num_microbatches=4)

    def test_pp_trainer_evaluate(self):
        """The pp loss-only eval pass: val_loss at the current params
        equals the loss the next train_step reports (train computes loss
        BEFORE applying the update), and perplexity = exp(val_loss)."""
        import math as _math

        from mpi_operator_tpu.train.lm_trainer import LMTrainerConfig
        from mpi_operator_tpu.train.pp_trainer import PipelineLMTrainer

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=128, max_len=16, num_layers=2)
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        trainer = PipelineLMTrainer(
            cfg, mesh, LMTrainerConfig(global_batch_size=16, seq_len=16,
                                       warmup_steps=1),
            num_microbatches=4)
        state = trainer.init_state(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (16, 17), 0, 128)
        batch = trainer.microbatch(toks[:, :-1], toks[:, 1:])

        class Rep:
            def __iter__(self):
                return iter([batch] * 4)

        ev = trainer.evaluate(state, Rep(), num_batches=1)
        _, m = trainer.train_step(state, *batch)
        np.testing.assert_allclose(ev["val_loss"], float(m["loss"]),
                                   atol=1e-5)
        assert ev["perplexity"] == pytest.approx(
            _math.exp(ev["val_loss"]), rel=1e-6)

    def test_masked_pp_trainer_step(self):
        """End-to-end pipelined BERT through PipelineLMTrainer
        (masked_lm=True): jitted step over the 3-stream (tokens, targets,
        mask) pipeline, loss decreases."""
        from mpi_operator_tpu.models.transformer import bert_config
        from mpi_operator_tpu.train.lm_trainer import LMTrainerConfig
        from mpi_operator_tpu.train.pp_trainer import PipelineLMTrainer

        cfg = bert_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=128, max_len=16)
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        trainer = PipelineLMTrainer(
            cfg, mesh,
            LMTrainerConfig(global_batch_size=16, seq_len=16,
                            masked_lm=True, warmup_steps=1),
            num_microbatches=4)
        state = trainer.init_state(jax.random.PRNGKey(0))
        orig = jax.random.randint(jax.random.PRNGKey(1), (16, 16), 0, 128)
        mask = (jax.random.uniform(jax.random.PRNGKey(2), (16, 16))
                < 0.3).astype(jnp.float32)
        toks = jnp.where(mask > 0, 127, orig)
        losses = []
        for _ in range(5):
            state, m = trainer.train_step(
                state, *trainer.microbatch(toks, orig, mask))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_pp_sp_trainer_step(self):
        """End-to-end pp×sp through PipelineLMTrainer: the jitted step
        (grads + optimizer over the sp-sharded stream) runs and the loss
        decreases."""
        from mpi_operator_tpu.train.lm_trainer import LMTrainerConfig
        from mpi_operator_tpu.train.pp_trainer import PipelineLMTrainer

        cfg = gpt2_config("test", attention="ring", dtype=jnp.float32,
                          vocab_size=128, max_len=16, num_layers=2)
        mesh = make_mesh(MeshConfig(pp=2, sp=2, dp=2))
        trainer = PipelineLMTrainer(
            cfg, mesh, LMTrainerConfig(global_batch_size=16, seq_len=16),
            num_microbatches=4)
        state = trainer.init_state(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (16, 17), 0, 128)
        tk, tg = toks[:, :-1], toks[:, 1:]
        losses = []
        for _ in range(4):
            state, m = trainer.train_step(state, *trainer.microbatch(tk, tg))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_bubble_fraction(self):
        from mpi_operator_tpu.parallel import bubble_fraction
        assert bubble_fraction(1, 8) == 0.0
        assert bubble_fraction(4, 12) == pytest.approx(3 / 15)
        # callers pick M >= 4P: bubble stays under 20%
        assert bubble_fraction(8, 32) < 0.2


# ---------------------------------------------------------------------------
# mesh plumbing for the new axes
# ---------------------------------------------------------------------------

def test_mesh_has_all_strategy_axes():
    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    assert set(mesh.axis_names) == {"dcn", "pp", "dp", "fsdp", "ep", "sp",
                                    "tp"}


def test_mesh_rejects_wrong_device_count():
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(dp=3, tp=5))


class TestPipelineTrainer:
    """End-to-end pp training: one PipelineLMTrainer step must equal one
    LMTrainer step on the same init, batch, and optimizer."""

    def _assert_matches_unpiped(self, mesh_cfg):
        """One PipelineLMTrainer step on `mesh_cfg` vs one LMTrainer step
        on a dp-only mesh: same loss, same params after sgd. Returns the
        pipeline state for sharding asserts."""
        import optax

        from mpi_operator_tpu.parallel import stack_lm_params
        from mpi_operator_tpu.train import (LMTrainer, LMTrainerConfig,
                                            PipelineLMTrainer)

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=256, max_len=32)
        tcfg = LMTrainerConfig(global_batch_size=16, seq_len=16)
        key = jax.random.PRNGKey(0)
        toks = jax.random.randint(jax.random.PRNGKey(5), (16, 17), 0,
                                  cfg.vocab_size)
        toks, tgts = toks[:, :-1], toks[:, 1:]

        ppt = PipelineLMTrainer(cfg, make_mesh(mesh_cfg), tcfg,
                                num_microbatches=4, tx=optax.sgd(0.1))
        s_pp = ppt.init_state(key)
        init_state = s_pp
        s_pp, m_pp = ppt.train_step(s_pp, *ppt.microbatch(toks, tgts))

        lmt = LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=8)), tcfg,
                        tx=optax.sgd(0.1))
        s_lm = lmt.init_state(key)
        s_lm, m_lm = lmt.train_step(s_lm, toks, tgts)

        np.testing.assert_allclose(float(m_pp["loss"]),
                                   float(m_lm["loss"]), atol=1e-5)
        ref = stack_lm_params(s_lm.params, cfg.num_layers)
        for (path, a), b in zip(
                jax.tree_util.tree_flatten_with_path(s_pp.params)[0],
                jax.tree.leaves(ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5,
                err_msg=jax.tree_util.keystr(path))
        return init_state

    @needs_partial_manual_spmd
    def test_one_step_matches_unpiped_trainer(self):
        self._assert_matches_unpiped(MeshConfig(pp=2, dp=4))

    @needs_partial_manual_spmd
    def test_pp_tp_composes_with_megatron_shardings(self):
        """pp×tp×dp: block params placed with Megatron tp shardings
        (lm_stage_tp_specs) while pipeline_lm_loss runs tp as a GSPMD auto
        axis — the step must still equal the unpiped LMTrainer step, and
        every Megatron leaf must ACTUALLY be tp-sharded (a param rename
        that silently falls through lm_stage_tp_specs' path matching must
        fail here, not quietly lose tensor parallelism)."""
        s_pp = self._assert_matches_unpiped(MeshConfig(pp=2, tp=2, dp=2))
        blocks = s_pp.params["blocks"]
        for leaf in (blocks["mlp"]["fc_in"]["kernel"],
                     blocks["mlp"]["fc_out"]["kernel"],
                     blocks["attn"]["query"]["kernel"],
                     blocks["attn"]["key"]["kernel"],
                     blocks["attn"]["value"]["kernel"],
                     blocks["attn"]["out"]["kernel"]):
            assert "tp" in str(leaf.sharding.spec), leaf.sharding

    def test_bubble_and_validation(self):
        import optax

        from mpi_operator_tpu.train import (LMTrainerConfig,
                                            PipelineLMTrainer)

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=64, max_len=16)
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        t = PipelineLMTrainer(cfg, mesh,
                              LMTrainerConfig(global_batch_size=32,
                                              seq_len=8),
                              num_microbatches=8, tx=optax.sgd(0.1))
        assert t.bubble == pytest.approx(1 / 9)
        with pytest.raises(ValueError):    # M must divide over pp
            PipelineLMTrainer(cfg, mesh,
                              LMTrainerConfig(global_batch_size=24,
                                              seq_len=8),
                              num_microbatches=3, tx=optax.sgd(0.1))
        with pytest.raises(ValueError):    # microbatch must divide over dp
            PipelineLMTrainer(cfg, mesh,
                              LMTrainerConfig(global_batch_size=16,
                                              seq_len=8),
                              num_microbatches=8, tx=optax.sgd(0.1))


class TestPipeline1F1B:
    """Interleaved 1F1B (parallel/pipeline_1f1b.py): same loss/grads as
    GPipe/unpiped, strictly smaller bubble with interleaving, O(P·v)
    in-flight memory by construction (VERDICT r02 next #4)."""

    def test_schedule_invariants(self):
        from mpi_operator_tpu.parallel.pipeline_1f1b import simulate_1f1b

        for (P, M, v) in [(2, 4, 1), (4, 8, 1), (4, 8, 2), (2, 8, 2),
                          (4, 16, 4)]:
            s = simulate_1f1b(P, M, v)
            VP = v * P
            done_f = np.full((VP, M), -1)
            done_b = np.full((VP, M), -1)
            for t in range(s.ticks):
                for d in range(P):
                    if s.dir[t, d] == 0:
                        continue
                    k = s.chunk[t, d] * P + d
                    m = s.mb[t, d]
                    if s.dir[t, d] == 1:
                        # fwd dependency: previous virtual stage finished
                        assert done_f[k, m] == -1
                        if k > 0:
                            assert 0 <= done_f[k - 1, m] < t
                        done_f[k, m] = t
                    else:
                        assert done_b[k, m] == -1
                        if k == VP - 1:
                            assert 0 <= done_f[k, m] < t
                        else:
                            assert 0 <= done_b[k + 1, m] < t
                        done_b[k, m] = t
            assert (done_f >= 0).all() and (done_b >= 0).all()

    def test_interleaving_shrinks_the_bubble(self):
        """The VERDICT criterion: measurably fewer idle ticks at pp=4.
        v=2 at pp=4/M=8 nearly halves the idle fraction; v=1 in-flight
        memory is O(P), not O(M)."""
        from mpi_operator_tpu.parallel.pipeline_1f1b import simulate_1f1b

        s1 = simulate_1f1b(4, 8, 1)
        s2 = simulate_1f1b(4, 8, 2)
        assert s2.bubble_fraction < 0.65 * s1.bubble_fraction
        assert s1.h_depth <= 4            # O(P): GPipe holds all M=8
        s_big = simulate_1f1b(4, 32, 1)
        assert s_big.h_depth <= 4         # independent of M

    def _parity(self, pp, dp, v, L):
        from flax.core import meta
        from mpi_operator_tpu.parallel.pipeline import (
            pipeline_lm_loss, stack_lm_params)
        from mpi_operator_tpu.parallel.pipeline_1f1b import (
            interleave_blocks, pipeline_lm_1f1b_grads)

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=128, max_len=16, num_layers=L)
        mesh = make_mesh(MeshConfig(pp=pp, dp=dp))
        model = CausalLM(cfg)
        M, mb, S = 2 * pp, 2, 16
        toks = jax.random.randint(jax.random.PRNGKey(1), (M, mb, S), 0, 128)
        tgts = jnp.roll(toks, -1, axis=-1)
        vs = meta.unbox(model.init(jax.random.PRNGKey(0),
                                   jnp.zeros((2, S), jnp.int32)))
        pp_params = stack_lm_params(vs["params"], cfg.num_layers)
        loss_g, grads_g = jax.jit(jax.value_and_grad(
            lambda p: pipeline_lm_loss(cfg, p, toks, tgts, mesh, M)))(
                pp_params)
        params_v = dict(pp_params)
        params_v["blocks"] = interleave_blocks(pp_params["blocks"], pp, v)
        loss_f, grads_f = jax.jit(lambda p: pipeline_lm_1f1b_grads(
            cfg, p, toks, tgts, mesh, M, interleave=v))(params_v)
        np.testing.assert_allclose(np.asarray(loss_g), np.asarray(loss_f),
                                   atol=1e-5)
        gb = interleave_blocks(grads_g["blocks"], pp, v)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5),
            gb, grads_f["blocks"])
        for k in ("wte", "wpe"):
            np.testing.assert_allclose(np.asarray(grads_g[k]),
                                       np.asarray(grads_f[k]), atol=1e-5)

    def test_1f1b_matches_gpipe_pp2(self):
        self._parity(pp=2, dp=4, v=1, L=2)

    def test_1f1b_interleaved_matches_gpipe(self):
        self._parity(pp=2, dp=4, v=2, L=4)

    @pytest.mark.parametrize("v", [1, 2])
    def test_1f1b_masked_matches_gpipe(self, v):
        """Masked-LM (BERT) under 1F1B (VERDICT r04 next #3): the mask is
        consumed at the last virtual stage, the divisor is the DYNAMIC
        global mask count — loss and grads must match the GPipe
        pipeline_mlm_loss + jax.grad on identical params."""
        from flax.core import meta
        from mpi_operator_tpu.models.transformer import (MaskedLM,
                                                         bert_config)
        from mpi_operator_tpu.parallel.pipeline import (pipeline_mlm_loss,
                                                        stack_mlm_params)
        from mpi_operator_tpu.parallel.pipeline_1f1b import (
            interleave_blocks, pipeline_lm_1f1b_grads)

        cfg = bert_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=128, max_len=16, num_layers=2 * v)
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        model = MaskedLM(cfg)
        M, mb, S = 4, 2, 16
        orig = jax.random.randint(jax.random.PRNGKey(1), (M, mb, S), 0, 128)
        msk = (jax.random.uniform(jax.random.PRNGKey(5), (M, mb, S))
               < 0.25).astype(jnp.float32)
        toks = jnp.where(msk > 0, cfg.vocab_size - 1, orig)
        vs = meta.unbox(model.init(jax.random.PRNGKey(0),
                                   jnp.zeros((2, S), jnp.int32)))
        pp_params = stack_mlm_params(vs["params"], cfg.num_layers)
        loss_g, grads_g = jax.jit(jax.value_and_grad(
            lambda p: pipeline_mlm_loss(cfg, p, toks, orig, msk, mesh, M)))(
                pp_params)
        params_v = dict(pp_params)
        params_v["blocks"] = interleave_blocks(pp_params["blocks"], 2, v)
        loss_f, grads_f = jax.jit(lambda p: pipeline_lm_1f1b_grads(
            cfg, p, toks, orig, mesh, M, interleave=v, mask=msk))(params_v)
        np.testing.assert_allclose(np.asarray(loss_g), np.asarray(loss_f),
                                   atol=2e-5)
        gb = interleave_blocks(grads_g["blocks"], 2, v)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4),
            gb, grads_f["blocks"])
        for k in ("wte", "mlm_bias", "mlm_dense", "ln_emb"):
            jax.tree.map(lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4),
                grads_g[k], grads_f[k])

    def test_1f1b_masked_sp_ring_matches_gpipe(self):
        """The full composition: masked-LM × sp × 1F1B — bidirectional
        ring stage bodies, sp-sharded mask stream, dynamic divisor, all
        under the in-schedule vjp. Pinned against the GPipe mlm path."""
        from flax.core import meta
        from mpi_operator_tpu.models.transformer import (MaskedLM,
                                                         bert_config)
        import dataclasses
        from mpi_operator_tpu.parallel.pipeline import (pipeline_mlm_loss,
                                                        stack_mlm_params)
        from mpi_operator_tpu.parallel.pipeline_1f1b import (
            pipeline_lm_1f1b_grads)

        cfg = bert_config("test", attention="ring", dtype=jnp.float32,
                          vocab_size=128, max_len=32)
        mesh = make_mesh(MeshConfig(pp=2, sp=2, dp=2))
        model = MaskedLM(dataclasses.replace(cfg, attention="dense"))
        M, mb, S = 4, 2, 32
        orig = jax.random.randint(jax.random.PRNGKey(1), (M, mb, S), 0, 128)
        msk = (jax.random.uniform(jax.random.PRNGKey(5), (M, mb, S))
               < 0.25).astype(jnp.float32)
        toks = jnp.where(msk > 0, cfg.vocab_size - 1, orig)
        vs = meta.unbox(model.init(jax.random.PRNGKey(0),
                                   jnp.zeros((2, S), jnp.int32)))
        pp_params = stack_mlm_params(vs["params"], cfg.num_layers)
        loss_g, grads_g = jax.jit(jax.value_and_grad(
            lambda p: pipeline_mlm_loss(cfg, p, toks, orig, msk, mesh, M)))(
                pp_params)
        loss_f, grads_f = jax.jit(lambda p: pipeline_lm_1f1b_grads(
            cfg, p, toks, orig, mesh, M, mask=msk))(pp_params)
        np.testing.assert_allclose(np.asarray(loss_g), np.asarray(loss_f),
                                   rtol=1e-4)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4),
            grads_g["blocks"], grads_f["blocks"])

    def test_1f1b_sp_ring_matches_gpipe(self):
        """pp×sp under 1F1B (VERDICT r04 next #3): the streams' sequence
        dim sharded over sp, stage attention ringing in-schedule — loss
        and grads must match the GPipe pp×sp path on identical params."""
        from flax.core import meta
        from mpi_operator_tpu.parallel.pipeline import (pipeline_lm_loss,
                                                        stack_lm_params)
        from mpi_operator_tpu.parallel.pipeline_1f1b import (
            pipeline_lm_1f1b_grads)

        cfg = gpt2_config("test", attention="ring", dtype=jnp.float32,
                          vocab_size=128, max_len=32)
        mesh = make_mesh(MeshConfig(pp=2, sp=2, dp=2))
        import dataclasses
        model = CausalLM(dataclasses.replace(cfg, attention="dense"))
        M, mb, S = 4, 2, 32
        toks = jax.random.randint(jax.random.PRNGKey(1), (M, mb, S), 0, 128)
        tgts = jnp.roll(toks, -1, axis=-1)
        vs = meta.unbox(model.init(jax.random.PRNGKey(0),
                                   jnp.zeros((2, S), jnp.int32)))
        pp_params = stack_lm_params(vs["params"], cfg.num_layers)
        loss_g, grads_g = jax.jit(jax.value_and_grad(
            lambda p: pipeline_lm_loss(cfg, p, toks, tgts, mesh, M)))(
                pp_params)
        loss_f, grads_f = jax.jit(lambda p: pipeline_lm_1f1b_grads(
            cfg, p, toks, tgts, mesh, M))(pp_params)
        # rtol, not tight atol: the 1F1B per-stage recompute-vjp orders
        # the ring reductions differently from GPipe's autodiff — f32
        # noise at ~2.5e-5 relative on this config
        np.testing.assert_allclose(np.asarray(loss_g), np.asarray(loss_f),
                                   rtol=1e-4)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4),
            grads_g["blocks"], grads_f["blocks"])

    def test_1f1b_trainer_step(self):
        """End-to-end: PipelineLMTrainer(schedule='1f1b', interleave=2)
        runs a full train step (grads in-schedule + optimizer) and the
        loss decreases over a few steps."""
        from mpi_operator_tpu.train.lm_trainer import LMTrainerConfig
        from mpi_operator_tpu.train.pp_trainer import PipelineLMTrainer

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=128, max_len=16, num_layers=4)
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        M, S = 4, 16
        tcfg = LMTrainerConfig(global_batch_size=4 * M, seq_len=S,
                               warmup_steps=1)
        tr = PipelineLMTrainer(cfg, mesh, tcfg, num_microbatches=M,
                               schedule="1f1b", interleave=2)
        state = tr.init_state(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(2),
                                  (tcfg.global_batch_size, S + 1), 0, 128)
        stream = tr.microbatch(toks[:, :-1], toks[:, 1:])
        losses = []
        for _ in range(5):
            state, m = tr.train_step(state, *stream)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses
        assert int(state.step) == 5

    def test_checkpoint_layout_is_schedule_agnostic(self):
        """Checkpoints are written in canonical layer order regardless of
        schedule, so a gpipe checkpoint resumes under 1f1b×2 (and back)
        without silently permuting layers."""
        from mpi_operator_tpu.train.lm_trainer import LMTrainerConfig
        from mpi_operator_tpu.train.pp_trainer import PipelineLMTrainer

        cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                          vocab_size=128, max_len=16, num_layers=4)
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        tcfg = LMTrainerConfig(global_batch_size=16, seq_len=16,
                               warmup_steps=1)
        g = PipelineLMTrainer(cfg, mesh, tcfg, num_microbatches=4)
        f = PipelineLMTrainer(cfg, mesh, tcfg, num_microbatches=4,
                              schedule="1f1b", interleave=2)
        gs = g.init_state(jax.random.PRNGKey(0))
        fs = f.init_state(jax.random.PRNGKey(0))
        # same seed → identical canonical params (the live layouts differ)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
            g.canonical_state(gs).params, f.canonical_state(fs).params)
        # evaluate() must de-interleave before the GPipe eval pass — with
        # the raw chunk layout the stages would apply layers out of order
        toks = jax.random.randint(jax.random.PRNGKey(3), (16, 17), 0, 128)
        batch = g.microbatch(toks[:, :-1], toks[:, 1:])

        class Rep:
            def __iter__(self):
                return iter([batch] * 2)

        ev_g = g.evaluate(gs, Rep(), num_batches=1)
        ev_f = f.evaluate(fs, Rep(), num_batches=1)
        np.testing.assert_allclose(ev_g["val_loss"], ev_f["val_loss"],
                                   rtol=1e-5)
        # live layouts really are permuted relative to each other
        diff = jax.tree.leaves(jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()),
            gs.params["blocks"], fs.params["blocks"]))
        assert max(diff) > 0
        # roundtrip is exact
        back = f.from_canonical_state(f.canonical_state(fs))
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), fs.params, back.params)


class TestMoeDropless:
    """VERDICT r02 weak #8: capacity dispatch drops load-imbalanced
    tokens silently. The drop RATE is now observable (sown intermediate)
    and a dropless mode exists."""

    def _m(self, **kw):
        from mpi_operator_tpu.parallel import MoeMlp
        base = dict(num_experts=4, embed_dim=32, mlp_dim=64, top_k=2,
                    capacity_factor=1.25, dtype=jnp.float32)
        base.update(kw)
        m = MoeMlp(**base)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32))
        vs = meta.unbox(m.init(jax.random.PRNGKey(1), x))
        return m, x, vs

    def _drop_rate(self, m, vs, x):
        (_, _), diag = m.apply(vs, x, mutable=["diagnostics"])
        return float(jax.tree.leaves(diag["diagnostics"])[0])

    def test_drop_rate_sane_at_default_capacity(self):
        """With a freshly-initialized (≈uniform) router and capacity
        factor 1.25, the drop rate must stay small — silent heavy
        dropping at the default config was the original complaint."""
        m, x, vs = self._m()
        rate = self._drop_rate(m, vs, x)
        assert 0.0 <= rate <= 0.25, rate

    def test_drop_rate_reports_starvation(self):
        m, x, vs = self._m(capacity_factor=0.01)
        rate = self._drop_rate(m, vs, x)
        assert rate >= 0.8, rate

    def test_dropless_matches_infinite_capacity(self):
        """Dropless == capacity dispatch with a budget nothing exceeds
        (same routing semantics, no dropped tokens)."""
        m_cap, x, vs = self._m(capacity_factor=100.0)
        ref, aux_ref = m_cap.apply(vs, x)
        m_free = self._m(dropless=True)[0]
        out, aux = m_free.apply(vs, x)        # identical param structure
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5)
        np.testing.assert_allclose(float(aux_ref), float(aux), atol=1e-6)
        assert self._drop_rate(m_free, vs, x) == 0.0

    def test_dropless_ep_sharded_matches_dense(self):
        """The dropless path still shards experts over ep."""
        from mpi_operator_tpu.parallel.sharding import param_shardings

        m, x, vs = self._m(dropless=True)
        ref, _ = m.apply(vs, x)
        mesh = make_mesh(MeshConfig(dp=2, ep=4))
        abstract = jax.eval_shape(lambda r: m.init(r, x),
                                  jax.random.PRNGKey(1))
        sh = param_shardings(mesh, abstract)
        out_sh = jax.tree.unflatten(
            jax.tree.structure(meta.unbox(abstract)), jax.tree.leaves(sh))
        vs_sharded = jax.jit(lambda v: v, out_shardings=out_sh)(vs)
        xs = jax.device_put(x, NamedSharding(mesh, P(("dp",))))
        out2, _ = jax.jit(m.apply)(vs_sharded, xs)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out2),
                                   atol=1e-5)


# ---------------------------------------------------------------------------
# ring collective-matmul (tp_overlap)
# ---------------------------------------------------------------------------

@pytest.mark.multichip
class TestRingCollectiveMatmul:
    """allgather_matmul / matmul_reducescatter against the einsum oracle.

    The ring decomposition (ppermute hops hidden behind per-shard matmuls)
    must be a pure re-schedule: same values forward AND backward, where the
    backward runs the mirrored ring via custom_vjp. Cotangents come from a
    nonlinear loss so each output element gets a distinct pullback.
    ring="bidir" halves each shard and rotates the halves in opposite
    directions (half the bytes per hop); with Sl=2 on the 4-ring below the
    halves are 1+1, so the odd-split arithmetic is exercised too."""

    def _mesh(self):
        return make_mesh(MeshConfig(dp=2, tp=4))

    @pytest.mark.parametrize("tp_ring", ["uni", "bidir"])
    def test_allgather_matmul_matches_einsum(self, tp_ring):
        from mpi_operator_tpu.parallel.collectives import allgather_matmul
        from mpi_operator_tpu.utils.compat import shard_map

        mesh = self._mesh()
        k0, k1 = jax.random.split(jax.random.PRNGKey(0))
        x = jax.random.normal(k0, (2, 8, 16), jnp.float32)    # rows over tp
        w = jax.random.normal(k1, (16, 12), jnp.float32)      # cols over tp

        ring = shard_map(
            lambda xl, wl: allgather_matmul(xl, wl, "tp", ring=tp_ring),
            mesh=mesh,
            in_specs=(P("dp", "tp", None), P(None, "tp")),
            out_specs=P("dp", None, "tp"), check_vma=False)

        def loss_ring(x, w):
            return jnp.sin(ring(x, w)).sum()

        def loss_ref(x, w):
            return jnp.sin(jnp.einsum("bsk,kn->bsn", x, w)).sum()

        np.testing.assert_allclose(
            np.asarray(ring(x, w)), np.asarray(x @ w), atol=1e-5)
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1)))(x, w)
        g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(x, w)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("tp_ring", ["uni", "bidir"])
    def test_matmul_reducescatter_matches_einsum(self, tp_ring):
        from mpi_operator_tpu.parallel.collectives import matmul_reducescatter
        from mpi_operator_tpu.utils.compat import shard_map

        mesh = self._mesh()
        k0, k1 = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.normal(k0, (2, 8, 16), jnp.float32)    # K over tp
        w = jax.random.normal(k1, (16, 12), jnp.float32)      # rows over tp

        ring = shard_map(
            lambda xl, wl: matmul_reducescatter(xl, wl, "tp", ring=tp_ring),
            mesh=mesh,
            in_specs=(P("dp", None, "tp"), P("tp", None)),
            out_specs=P("dp", "tp", None), check_vma=False)

        def loss_ring(x, w):
            return jnp.sin(ring(x, w)).sum()

        def loss_ref(x, w):
            return jnp.sin(jnp.einsum("bsk,kn->bsn", x, w)).sum()

        np.testing.assert_allclose(
            np.asarray(ring(x, w)), np.asarray(x @ w), atol=1e-5)
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1)))(x, w)
        g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(x, w)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("tp_ring", ["uni", "bidir"])
    def test_non_divisible_rows_padded(self, tp_ring):
        """S=6 over a 4-ring: the internal zero-row pad takes it to 8,
        pad rows land at the END of the global output (highest ranks) as
        exact zeros, and grads flow correctly through the caller's
        slice."""
        from mpi_operator_tpu.parallel.collectives import matmul_reducescatter
        from mpi_operator_tpu.utils.compat import shard_map

        mesh = self._mesh()
        k0, k1 = jax.random.split(jax.random.PRNGKey(3))
        x = jax.random.normal(k0, (6, 16), jnp.float32)
        w = jax.random.normal(k1, (16, 12), jnp.float32)
        f = shard_map(
            lambda xl, wl: matmul_reducescatter(xl, wl, "tp", ring=tp_ring),
            mesh=mesh,
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None), check_vma=False)
        out = f(x, w)
        assert out.shape == (8, 12)              # 4 * ceil(6/4)
        np.testing.assert_allclose(np.asarray(out[:6]), np.asarray(x @ w),
                                   atol=1e-5)
        assert np.all(np.asarray(out[6:]) == 0.0)

        def loss_ring(x, w):
            return jnp.sin(f(x, w)[:6]).sum()    # caller slices the pad

        def loss_ref(x, w):
            return jnp.sin(x @ w).sum()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1)))(x, w)
        g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(x, w)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_contraction_mismatch_rejected(self):
        from mpi_operator_tpu.parallel.collectives import allgather_matmul

        with pytest.raises(ValueError, match="contraction mismatch"):
            allgather_matmul(jnp.ones((4, 8)), jnp.ones((16, 4)))

    def test_tp_overlap_train_step_matches_oracle(self):
        """tp_overlap=True is a latency optimization, never a numerics
        change: the full train step (qkv/out/ffn rings + the overlapped
        fused LM loss) must track the einsum path loss-for-loss across an
        optimizer update."""
        import optax

        from mpi_operator_tpu.train import LMTrainer, LMTrainerConfig

        toks = jax.random.randint(jax.random.PRNGKey(5), (8, 17), 0, 256)
        toks, tgts = toks[:, :-1], toks[:, 1:]
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        outs = {}
        for mode in ("einsum", "uni", "bidir"):
            cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                              vocab_size=256, max_len=32,
                              tp_overlap=mode != "einsum",
                              tp_ring="bidir" if mode == "bidir" else "uni")
            t = LMTrainer(CausalLM(cfg), mesh,
                          LMTrainerConfig(global_batch_size=8, seq_len=16,
                                          fused_xent=True),
                          tx=optax.sgd(0.1))
            s = t.init_state(jax.random.PRNGKey(0))
            s, m1 = t.train_step(s, toks, tgts)
            s, m2 = t.train_step(s, toks, tgts)   # after a real update
            outs[mode] = (float(m1["loss"]), float(m2["loss"]))
        np.testing.assert_allclose(outs["uni"], outs["einsum"], rtol=2e-6)
        np.testing.assert_allclose(outs["bidir"], outs["einsum"], rtol=2e-6)

    def test_tp_overlap_non_divisible_seq_and_vocab(self):
        """seq=15 and vocab=255 over tp=2: the overlap bodies zero-pad
        internally (seq rows masked out, pad vocab columns forced to
        -inf before the softmax normalizer) instead of raising — the
        loss must equal the einsum path's exactly."""
        import optax

        from mpi_operator_tpu.train import LMTrainer, LMTrainerConfig

        toks = jax.random.randint(jax.random.PRNGKey(9), (8, 16), 0, 255)
        toks, tgts = toks[:, :-1], toks[:, 1:]
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        outs = {}
        for mode in ("einsum", "uni", "bidir"):
            cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                              vocab_size=255, max_len=32,
                              tp_overlap=mode != "einsum",
                              tp_ring="bidir" if mode == "bidir" else "uni")
            t = LMTrainer(CausalLM(cfg), mesh,
                          LMTrainerConfig(global_batch_size=8, seq_len=15,
                                          fused_xent=True),
                          tx=optax.sgd(0.1))
            s = t.init_state(jax.random.PRNGKey(0))
            s, m1 = t.train_step(s, toks, tgts)
            s, m2 = t.train_step(s, toks, tgts)
            outs[mode] = (float(m1["loss"]), float(m2["loss"]))
        np.testing.assert_allclose(outs["uni"], outs["einsum"], rtol=2e-6)
        np.testing.assert_allclose(outs["bidir"], outs["einsum"], rtol=2e-6)
