"""PrefetchDataset double-buffering contract (data/prefetch.py): FIFO
ordering through the feeder thread, bounded read-ahead depth, clean
StopIteration on producer exhaustion (sticky — no deadlock on the next
next()), and feeder errors surfacing in the consumer."""
import threading
import time

import pytest

from mpi_operator_tpu.data.prefetch import PrefetchDataset


class _ListDataset(PrefetchDataset):
    """Finite producer over `items`; optionally gates each yield on an
    event so tests can control how far ahead the feeder runs."""

    def __init__(self, items, prefetch=2, gate=None, fail_at=None):
        self.items = list(items)
        self.gate = gate
        self.fail_at = fail_at
        self.produced = 0
        self._start_feeder(prefetch)

    def _produce(self):
        for it in self.items:
            if self.gate is not None:
                self.gate.wait()
            if self.fail_at is not None and self.produced == self.fail_at:
                raise ValueError("injected producer failure")
            self.produced += 1
            yield it


def test_prefetch_preserves_order():
    ds = _ListDataset(range(50), prefetch=3)
    try:
        assert list(ds) == list(range(50))
    finally:
        ds.close()


def test_prefetch_depth_is_bounded():
    # an unconsumed iterator may run at most `prefetch` items ahead into
    # the queue plus one more blocked in put() — never the whole stream
    ds = _ListDataset(range(100), prefetch=2)
    try:
        deadline = time.monotonic() + 5.0
        while ds.produced < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)                      # would overrun here if unbounded
        assert ds.produced <= 3              # prefetch + 1 in-flight
        assert next(ds) == 0                 # consuming frees one slot
        deadline = time.monotonic() + 5.0
        while ds.produced < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert 4 <= ds.produced <= 4
    finally:
        ds.close()


def test_prefetch_exhaustion_raises_stopiteration_repeatably():
    ds = _ListDataset([1, 2], prefetch=2)
    try:
        assert next(ds) == 1
        assert next(ds) == 2
        with pytest.raises(StopIteration):
            next(ds)
        # sticky: a second next() must raise again, not block forever
        with pytest.raises(StopIteration):
            next(ds)
        # and a plain for-loop over a fresh instance terminates
        ds2 = _ListDataset("ab", prefetch=1)
        assert list(ds2) == ["a", "b"]
        ds2.close()
    finally:
        ds.close()


def test_prefetch_feeder_error_surfaces_in_consumer():
    ds = _ListDataset(range(10), prefetch=2, fail_at=1)
    try:
        assert next(ds) == 0
        with pytest.raises(RuntimeError, match="feeder thread failed"):
            # drain until the wrapped producer exception arrives
            for _ in range(10):
                next(ds)
    finally:
        ds.close()


def test_prefetch_close_unblocks_feeder():
    gate = threading.Event()
    gate.set()
    ds = _ListDataset(range(10_000), prefetch=1, gate=gate)
    try:
        assert next(ds) == 0
    finally:
        ds.close()
    assert not ds._thread.is_alive()
