"""Resharding restore (train/checkpoint.restore_resharded): load a
checkpoint saved on one mesh into a DIFFERENT mesh by resharding on
read — per-leaf parallel shard reads, byte-range sub-domain fetches,
regex restore rules, fallback composition, and the host-memory pin."""
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_operator_tpu.parallel import path_match, spec_for_path
from mpi_operator_tpu.train.checkpoint import (
    ReadStats, maybe_resume, reset_saved_state, restore_resharded,
    restore_with_fallback, save_checkpoint, wait_for_checkpoints,
)
from mpi_operator_tpu.train.resilience import corrupt_latest_checkpoint


class _State(struct.PyTreeNode):
    step: Any
    params: Any
    opt_state: Any


#: deterministic leaf contents — the single-host oracle every mesh pair
#: must reproduce bitwise
_ORACLE = {
    "kernel": np.arange(8 * 4, dtype=np.float32).reshape(8, 4),
    "bias": np.arange(4, dtype=np.float32) * 0.5,
    "emb": np.arange(16 * 8, dtype=np.float32).reshape(16, 8) - 7.0,
}


def _mesh(dp: int, tp: int) -> Mesh:
    devs = np.array(jax.devices()[: dp * tp]).reshape(dp, tp)
    return Mesh(devs, ("dp", "tp"))


def _state_on(mesh: Mesh, step: int = 3) -> _State:
    def put(name, spec):
        return jax.device_put(_ORACLE[name], NamedSharding(mesh, spec))

    params = {"dense": {"kernel": put("kernel", P("dp", "tp")),
                        "bias": put("bias", P("tp"))},
              "emb": put("emb", P(None, "tp"))}
    opt_state = ({"mu": {"dense": {"kernel": put("kernel", P("dp", "tp")),
                                   "bias": put("bias", P("tp"))},
                         "emb": put("emb", P(None, "tp"))}},)
    return _State(step=jnp.asarray(step, jnp.int32), params=params,
                  opt_state=opt_state)


def _assert_oracle(state: _State, target: _State) -> None:
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    want = {"kernel": _ORACLE["kernel"], "bias": _ORACLE["bias"],
            "emb": _ORACLE["emb"]}
    for path, leaf in flat:
        name = str(path[-1].key)
        np.testing.assert_array_equal(np.asarray(leaf), want[name])
    for got, tgt in zip(jax.tree.leaves(state.params),
                        jax.tree.leaves(target.params)):
        assert got.sharding == tgt.sharding   # landed in the NEW layout


# every save mesh restores onto a rotated DIFFERENT mesh; the (1, 1)
# target doubles as the single-host full-replica oracle
_SHAPES = [(1, 1), (2, 1), (4, 1), (1, 2), (2, 2), (4, 2)]


@pytest.mark.parametrize("save_shape,restore_shape",
                         list(zip(_SHAPES, _SHAPES[1:] + _SHAPES[:1])),
                         ids=lambda s: f"dp{s[0]}xtp{s[1]}")
def test_reshard_restore_bitwise_across_meshes(tmp_path, save_shape,
                                               restore_shape):
    reset_saved_state()
    save_checkpoint(tmp_path, _state_on(_mesh(*save_shape)))
    target = _state_on(_mesh(*restore_shape), step=0)
    target = jax.tree.map(jnp.zeros_like, target)
    stats = ReadStats()
    restored = restore_resharded(str(tmp_path), target, stats=stats)
    assert int(restored.step) == 3
    _assert_oracle(restored, _state_on(_mesh(*restore_shape)))
    assert stats.leaves == 7 and stats.seconds > 0
    assert stats.bytes_read >= max(
        int(np.prod(l.shape, initial=1)) * l.dtype.itemsize
        for l in jax.tree.leaves(target.params))


def test_reshard_restore_rules_override(tmp_path):
    """Regex restore rules rewrite the landing sharding per leaf —
    windowed over the tree path, first hit wins, None replicates."""
    reset_saved_state()
    mesh = _mesh(2, 2)
    save_checkpoint(tmp_path, _state_on(_mesh(4, 1)))
    target = _state_on(mesh, step=0)
    rules = [(("params", ".*", "bias"), None),          # replicate
             (("emb",), P("dp", "tp"))]
    restored = restore_resharded(str(tmp_path), target, rules=rules)
    bias = restored.params["dense"]["bias"]
    assert bias.sharding.is_fully_replicated
    emb_spec = restored.params["emb"].sharding.spec
    assert emb_spec == P("dp", "tp")
    # un-matched leaves keep the target state's own sharding
    assert (restored.params["dense"]["kernel"].sharding
            == target.params["dense"]["kernel"].sharding)
    _oracle_flat = {k: v for k, v in _ORACLE.items()}
    np.testing.assert_array_equal(np.asarray(bias), _oracle_flat["bias"])
    np.testing.assert_array_equal(np.asarray(restored.params["emb"]),
                                  _oracle_flat["emb"])


def test_corrupt_newest_falls_back_across_reshard(tmp_path):
    """A scribbled newest checkpoint falls back to the previous step even
    when the restore also changes the mesh (restore_with_fallback
    composing with the resharding reader via maybe_resume)."""
    reset_saved_state()
    old = _mesh(4, 1)
    save_checkpoint(tmp_path, _state_on(old, step=1), step=1)
    save_checkpoint(tmp_path, _state_on(old, step=2), step=2)
    assert corrupt_latest_checkpoint(str(tmp_path)).endswith("step_2")
    target = jax.tree.map(jnp.zeros_like, _state_on(_mesh(2, 2), step=0))
    logs = []
    restored = maybe_resume(str(tmp_path), target, logs.append,
                            reshard=True)
    assert int(restored.step) == 1
    _assert_oracle(restored, _state_on(_mesh(2, 2)))
    assert any("WARNING" in l and "step_2" in l for l in logs)
    # satellite contract: the fallback logs restore wall time + leaf count
    assert any("INFO: restored" in l and "leaves)" in l for l in logs)


def test_reshard_restore_memory_pin(tmp_path):
    """Peak in-flight host bytes stay pinned to one leaf's working set
    (max_workers=1): the reader never materializes the whole checkpoint
    on the host the way a load-then-shard restore would."""
    reset_saved_state()
    big = {f"w{i}": np.full((64, 8), float(i), np.float32)
           for i in range(6)}
    mesh_a, mesh_b = _mesh(4, 1), _mesh(2, 2)

    def on(mesh, tree):
        return jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P("dp"))),
            tree)

    state = _State(step=jnp.asarray(1, jnp.int32), params=on(mesh_a, big),
                   opt_state=())
    save_checkpoint(tmp_path, state)
    target = _State(step=jnp.asarray(0, jnp.int32),
                    params=on(mesh_b, jax.tree.map(np.zeros_like, big)),
                    opt_state=())
    stats = ReadStats()
    restored = restore_resharded(str(tmp_path), target, max_workers=1,
                                 stats=stats)
    for i in range(6):
        np.testing.assert_array_equal(np.asarray(restored.params[f"w{i}"]),
                                      big[f"w{i}"])
    leaf_bytes = 64 * 8 * 4
    assert stats.total_bytes >= 6 * leaf_bytes
    # the pin: at most one leaf's bytes resident at any instant, well
    # under the full-replica footprint
    assert 0 < stats.peak_in_flight_bytes <= leaf_bytes
    assert stats.peak_in_flight_bytes < stats.total_bytes


def test_restore_resharded_shape_mismatch_raises(tmp_path):
    reset_saved_state()
    save_checkpoint(tmp_path, _state_on(_mesh(2, 2)))
    wrong = _State(step=jnp.asarray(0, jnp.int32),
                   params={"dense": {"kernel": jax.device_put(
                       np.zeros((4, 4), np.float32),
                       NamedSharding(_mesh(2, 1), P("dp")))},
                       "emb": jax.device_put(
                           np.zeros((16, 8), np.float32),
                           NamedSharding(_mesh(2, 1), P("dp")))},
                   opt_state=())
    with pytest.raises((ValueError, KeyError)):
        restore_resharded(str(tmp_path), wrong)


def test_restore_with_fallback_logs_wall_time(tmp_path):
    """Satellite 6: every restore (resharded or not) logs wall time and
    leaf count at INFO."""
    reset_saved_state()
    save_checkpoint(tmp_path, _state_on(_mesh(2, 2), step=5))
    logs = []
    restored, path = restore_with_fallback(
        str(tmp_path), _state_on(_mesh(2, 2), step=0), logs.append)
    assert path.endswith("step_5") and int(restored.step) == 5
    info = [l for l in logs if l.startswith("INFO: restored")]
    assert len(info) == 1
    assert " in " in info[0] and info[0].rstrip().endswith("leaves)")


def test_path_match_and_spec_rules():
    assert path_match(("params", ".*kernel"),
                      ("params", "blocks_0", "attn", "kernel")) is False
    assert path_match(("params", ".*", "kernel"),
                      ("params", "attn", "kernel"))
    assert path_match((".*kernel",), ("opt_state", "0", "mu", "kernel"))
    # anchored per component: "kern" must not match "kernel"
    assert not path_match(("kern",), ("kernel",))
    rules = [(("bias",), None), ((".*", "kernel"), P("tp"))]
    assert spec_for_path(("params", "bias"), rules) == P()
    assert spec_for_path(("params", "x", "kernel"), rules) == P("tp")
    assert spec_for_path(("params", "other"), rules) is None
    assert spec_for_path(("params", "other"), rules,
                         default=P("dp")) == P("dp")


def teardown_module(module):
    wait_for_checkpoints()
