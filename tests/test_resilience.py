"""Preemption-tolerance tests (train/resilience.py + checkpoint fallback).

The fault-injection harness (TPU_FAULT_INJECT) lets a CPU mesh prove the
kill→restart→resume story end to end: the e2e test below SIGTERMs a run
mid-training, asserts the emergency checkpoint, resumes, and checks the
restarted run reaches the SAME final step with bitwise-identical params
(the streams are step-keyed, so resumption is token-identical).
"""
import os
import signal
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import struct

from mpi_operator_tpu.train.resilience import (
    FAULT_DIE_EXIT, PREEMPTED_EXIT, WATCHDOG_STALL_EXIT,
    DivergenceError, FaultInjector, Preempted, PreemptionListener,
    ResilienceConfig, ResilienceContext, Watchdog, corrupt_latest_checkpoint,
    guard_nonfinite_update, is_retryable_exit,
)
from mpi_operator_tpu.train.checkpoint import (
    checkpoint_steps, gc_checkpoints, latest_checkpoint, maybe_resume,
    maybe_save, periodic_saver, reset_saved_state, restore_with_fallback,
    save_checkpoint, verify_checkpoint, wait_for_checkpoints,
)


# ---------------------------------------------------------------------------
# Minimal checkpointable state: checkpoint.py persists step/params/opt_state
# and rollback resets nonfinite_streak — no model/trainer needed to test
# the storage layer.
# ---------------------------------------------------------------------------

class _CkptState(struct.PyTreeNode):
    step: Any
    params: Any
    opt_state: Any
    nonfinite_streak: Any = 0


def _ckpt_state(step: int, value: float) -> _CkptState:
    return _CkptState(step=jnp.asarray(step, jnp.int32),
                      params={"w": jnp.full((4,), value, jnp.float32)},
                      opt_state={"m": jnp.zeros((4,), jnp.float32)})


# ---------------------------------------------------------------------------
# Exit codes / fault-spec parsing
# ---------------------------------------------------------------------------

def test_exit_codes_sit_in_retryable_band():
    from mpi_operator_tpu.bootstrap.bootstrap import LAUNCHER_LOST_EXIT
    codes = {PREEMPTED_EXIT, WATCHDOG_STALL_EXIT, FAULT_DIE_EXIT,
             LAUNCHER_LOST_EXIT}
    assert len(codes) == 4                  # all distinct (diagnosable)
    for code in codes:
        assert 128 <= code <= 255 and is_retryable_exit(code)
    assert is_retryable_exit(None)          # signal-killed pod
    assert not is_retryable_exit(0) and not is_retryable_exit(1)


def test_fault_spec_parsing():
    f = FaultInjector("die-at-step:7; sigterm-at-step:3,"
                      "corrupt-latest-checkpoint;delay-coordinator:2")
    assert f.die_at_step == 7 and f.sigterm_at_step == 3
    assert f.corrupt_latest and f.delay_coordinator == 2
    # the init-failure budget is consumed exactly delay_coordinator times
    assert f.fail_init_attempt() and f.fail_init_attempt()
    assert not f.fail_init_attempt()
    assert FaultInjector.from_env({}) is None
    got = FaultInjector.from_env({"TPU_FAULT_INJECT": "die-at-step:9"})
    assert got is not None and got.die_at_step == 9
    with pytest.raises(ValueError, match="unknown"):
        FaultInjector("die-at-step:7;tpyo-directive:1")


def test_preempted_carries_retryable_exit_code():
    p = Preempted(41)
    assert p.step == 41 and p.exit_code == PREEMPTED_EXIT
    assert is_retryable_exit(p.exit_code)


# ---------------------------------------------------------------------------
# Signal listener
# ---------------------------------------------------------------------------

def test_preemption_listener_flags_and_chains():
    chained = []
    prev = lambda signum, frame: chained.append(signum)  # noqa: E731
    old = signal.signal(signal.SIGUSR1, prev)
    try:
        listener = PreemptionListener(log=lambda s: None).install()
        try:
            assert not listener.requested
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.monotonic() + 5.0
            while not listener.requested and time.monotonic() < deadline:
                time.sleep(0.01)
            assert listener.requested
            assert chained == [signal.SIGUSR1]     # prev handler chained
        finally:
            listener.uninstall()
        # uninstall restored the pre-existing handler
        assert signal.getsignal(signal.SIGUSR1) is prev
    finally:
        signal.signal(signal.SIGUSR1, old)


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------

def test_watchdog_fires_on_stall():
    fired = []
    dog = Watchdog(deadline=0.2, log=lambda s: None,
                   abort=fired.append, poll=0.05)
    dog.start()
    try:
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        dog.stop()
    assert fired == [WATCHDOG_STALL_EXIT]


def test_watchdog_stays_quiet_while_petted():
    fired = []
    dog = Watchdog(deadline=0.3, log=lambda s: None,
                   abort=fired.append, poll=0.05)
    dog.start()
    try:
        for _ in range(12):                # 0.6s of healthy steps
            dog.pet()
            time.sleep(0.05)
    finally:
        dog.stop()
    assert fired == []


# ---------------------------------------------------------------------------
# Divergence guard (pure pytree semantics — no model needed)
# ---------------------------------------------------------------------------

class _GuardState(struct.PyTreeNode):
    step: Any
    params: Any
    nonfinite_streak: Any = 0


def test_guard_nonfinite_update_semantics():
    old = _GuardState(step=jnp.asarray(5, jnp.int32),
                      params={"w": jnp.ones((3,))},
                      nonfinite_streak=jnp.asarray(0, jnp.int32))
    new = old.replace(step=old.step + 1,
                      params={"w": jnp.full((3,), 2.0)})
    grads = {"w": jnp.ones((3,))}

    ok = guard_nonfinite_update(old, new, jnp.asarray(1.25), grads)
    np.testing.assert_array_equal(np.asarray(ok.params["w"]), 2.0)
    assert int(ok.step) == 6 and int(ok.nonfinite_streak) == 0

    bad_loss = guard_nonfinite_update(old, new, jnp.asarray(jnp.nan), grads)
    np.testing.assert_array_equal(np.asarray(bad_loss.params["w"]), 1.0)
    # the step STILL advances: a skipped step is a no-op update, not a
    # rewind (checkpoint naming stays monotonic)
    assert int(bad_loss.step) == 6 and int(bad_loss.nonfinite_streak) == 1

    bad_grad = guard_nonfinite_update(
        old, new, jnp.asarray(0.5), {"w": jnp.array([1.0, jnp.inf, 0.0])})
    np.testing.assert_array_equal(np.asarray(bad_grad.params["w"]), 1.0)
    assert int(bad_grad.nonfinite_streak) == 1

    streaky = old.replace(nonfinite_streak=jnp.asarray(2, jnp.int32))
    worse = guard_nonfinite_update(streaky, new, jnp.asarray(jnp.nan), grads)
    assert int(worse.nonfinite_streak) == 3
    reset = guard_nonfinite_update(streaky, new, jnp.asarray(0.5), grads)
    assert int(reset.nonfinite_streak) == 0


def test_trainer_skips_nonfinite_step():
    """Integration: a NaN batch through the real jitted step applies NO
    update (params/opt state/BN stats identical) and increments the
    streak; the next clean batch resets it and trains normally."""
    from mpi_operator_tpu.data import synthetic_image_batch
    from mpi_operator_tpu.models.resnet import create_model
    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.train import Trainer, TrainerConfig

    mesh = make_mesh(MeshConfig.data_parallel(8))
    trainer = Trainer(create_model("resnet18", num_classes=10,
                                   dtype=jnp.float32), mesh,
                      TrainerConfig(global_batch_size=16, image_size=32,
                                    num_classes=10))
    state = trainer.init_state(jax.random.PRNGKey(0))
    imgs, labels = synthetic_image_batch(
        jax.random.PRNGKey(1), 16, image_size=32, num_classes=10,
        dtype=jnp.float32)
    imgs = jax.device_put(imgs, trainer.batch_sharding)
    labels = jax.device_put(labels, trainer.batch_sharding)
    bad = jax.device_put(jnp.full_like(imgs, jnp.nan),
                         trainer.batch_sharding)

    before = jax.tree.map(jnp.copy, state.params)
    state, m = trainer.train_step(state, bad, labels)
    assert not np.isfinite(float(m["loss"]))
    assert int(m["nonfinite_streak"]) == 1
    assert int(state.step) == 1                # monotonic step counter
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    state, m = trainer.train_step(state, imgs, labels)
    assert np.isfinite(float(m["loss"]))
    assert int(m["nonfinite_streak"]) == 0     # clean step resets
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(before),
                        jax.tree.leaves(state.params)))
    assert changed                             # the clean step DID train


# ---------------------------------------------------------------------------
# Checkpoint integrity, fallback, retention
# ---------------------------------------------------------------------------

def test_verify_rejects_missing_metadata(tmp_path):
    save_checkpoint(tmp_path, _ckpt_state(1, 1.0), step=1)
    path2 = save_checkpoint(tmp_path, _ckpt_state(2, 2.0), step=2)
    assert verify_checkpoint(path2)
    os.remove(os.path.join(path2, "_METADATA"))       # torn write
    assert not verify_checkpoint(path2)
    # latest skips the torn candidate and falls back a step
    assert latest_checkpoint(str(tmp_path)).endswith("step_1")


def test_corrupted_newest_falls_back_with_warning(tmp_path):
    save_checkpoint(tmp_path, _ckpt_state(1, 1.0), step=1)
    save_checkpoint(tmp_path, _ckpt_state(2, 2.0), step=2)
    corrupted = corrupt_latest_checkpoint(str(tmp_path))
    assert corrupted.endswith("step_2")
    # the scribbled directory still LOOKS committed — only the restore
    # itself can catch it
    logs = []
    restored, path = restore_with_fallback(str(tmp_path),
                                           _ckpt_state(0, 0.0), logs.append)
    assert path.endswith("step_1") and int(restored.step) == 1
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 1.0)
    assert any("WARNING" in l and "step_2" in l for l in logs)

    logs2 = []
    resumed = maybe_resume(str(tmp_path), _ckpt_state(0, 0.0), logs2.append)
    assert int(resumed.step) == 1
    assert any("resumed from" in l for l in logs2)


def test_gc_checkpoints_keep_last(tmp_path):
    for n in range(1, 6):
        save_checkpoint(tmp_path, _ckpt_state(n, float(n)), step=n)
    assert gc_checkpoints(str(tmp_path), keep_last=0) == []   # disabled
    logs = []
    assert gc_checkpoints(str(tmp_path), 2, logs.append) == [1, 2, 3]
    assert checkpoint_steps(str(tmp_path)) == [4, 5]
    assert any("checkpoint gc" in l for l in logs)
    assert gc_checkpoints(str(tmp_path), 2) == []             # idempotent


def test_periodic_saver_gc_bounds_retention(tmp_path):
    logs = []
    hook = periodic_saver(str(tmp_path), every=1, log=logs.append,
                          keep_last=2)
    for n in range(1, 5):
        hook(_ckpt_state(n, float(n)), n)
    wait_for_checkpoints()
    steps = checkpoint_steps(str(tmp_path))
    assert 1 not in steps                       # oldest collected
    assert steps[-2:] == [3, 4]                 # newest retained
    assert len(steps) <= 3                      # keep_last + in-flight


def test_maybe_save_skip_and_reset(tmp_path):
    logs = []
    maybe_save(str(tmp_path), _ckpt_state(3, 1.0), logs.append)
    assert any("written to" in l for l in logs)
    logs.clear()
    maybe_save(str(tmp_path), _ckpt_state(3, 1.0), logs.append)
    assert any("already written" in l for l in logs)   # skip, no rewrite
    reset_saved_state()
    logs.clear()
    maybe_save(str(tmp_path), _ckpt_state(3, 1.0), logs.append)
    assert any("written to" in l for l in logs)        # record forgotten


# ---------------------------------------------------------------------------
# ResilienceContext: stop bit, emergency save, rollback budget
# ---------------------------------------------------------------------------

def test_context_sigterm_fault_drains_deterministically(tmp_path):
    cfg = ResilienceConfig(train_dir=str(tmp_path))
    with ResilienceContext(cfg, log=lambda s: None,
                           faults=FaultInjector("sigterm-at-step:3")) as ctx:
        assert not ctx.on_step(1) and not ctx.on_step(2)
        assert ctx.on_step(3)       # injected preemption, deterministic
        ctx.emergency_save(_ckpt_state(3, 3.0))
    assert latest_checkpoint(str(tmp_path)).endswith("step_3")


def test_context_rollback_restores_and_budgets(tmp_path):
    save_checkpoint(tmp_path, _ckpt_state(2, 2.0), step=2)
    logs = []
    ctx = ResilienceContext(
        ResilienceConfig(train_dir=str(tmp_path), max_rollbacks=2),
        log=logs.append)
    diverged = _ckpt_state(5, 999.0).replace(
        nonfinite_streak=jnp.asarray(3, jnp.int32))
    rolled = ctx.rollback(diverged)
    assert int(rolled.step) == 2 and int(rolled.nonfinite_streak) == 0
    np.testing.assert_array_equal(np.asarray(rolled.params["w"]), 2.0)
    assert any("divergence rollback #1" in l for l in logs)
    ctx.rollback(diverged)                      # budget: second is fine
    with pytest.raises(DivergenceError, match="giving up"):
        ctx.rollback(diverged)                  # third exceeds max_rollbacks


def test_context_rollback_without_checkpoints_raises(tmp_path):
    ctx = ResilienceContext(ResilienceConfig(train_dir=str(tmp_path)),
                            log=lambda s: None)
    with pytest.raises(DivergenceError, match="no restorable checkpoint"):
        ctx.rollback(_ckpt_state(5, 1.0))
    ctx2 = ResilienceContext(ResilienceConfig(train_dir=None),
                             log=lambda s: None)
    with pytest.raises(DivergenceError, match="no --train-dir"):
        ctx2.rollback(_ckpt_state(5, 1.0))


def test_context_enter_fires_corrupt_fault(tmp_path):
    save_checkpoint(tmp_path, _ckpt_state(1, 1.0), step=1)
    save_checkpoint(tmp_path, _ckpt_state(2, 2.0), step=2)
    logs = []
    with ResilienceContext(
            ResilienceConfig(train_dir=str(tmp_path)), log=logs.append,
            faults=FaultInjector("corrupt-latest-checkpoint")):
        # __enter__ scribbled step_2 BEFORE any resume would run
        assert any("fault-inject: corrupted" in l for l in logs)
        restored, path = restore_with_fallback(
            str(tmp_path), _ckpt_state(0, 0.0), logs.append)
        assert path.endswith("step_1")


# ---------------------------------------------------------------------------
# The acceptance e2e: SIGTERM mid-run → emergency checkpoint → resume →
# token-identical final state at the same global step.
# ---------------------------------------------------------------------------

def _tiny_lm(train_dir, log, **kw):
    from mpi_operator_tpu.examples.lm_benchmark import run_lm_benchmark
    return run_lm_benchmark(
        workload="gpt2", size="test", batch_per_device=1, seq_len=16,
        dtype_name="float32", warmup_steps=1, train_dir=train_dir,
        log=log, **kw)


def test_e2e_sigterm_resume_token_identical(tmp_path, monkeypatch):
    monkeypatch.delenv("TPU_FAULT_INJECT", raising=False)
    # A: uninterrupted — 1 warmup + 7 timed steps → global step 8
    state_a, _ = _tiny_lm(str(tmp_path / "a"), lambda s: None, num_steps=7)
    assert int(state_a.step) == 8

    # B1: preempted at step 4 — the gang drains, writes the emergency
    # checkpoint, raises Preempted (entrypoints turn it into exit 215)
    logs = []
    monkeypatch.setenv("TPU_FAULT_INJECT", "sigterm-at-step:4")
    with pytest.raises(Preempted) as exc:
        _tiny_lm(str(tmp_path / "b"), logs.append, num_steps=7)
    assert exc.value.step == 4 and exc.value.exit_code == PREEMPTED_EXIT
    assert any("preemption drain" in l for l in logs)
    assert latest_checkpoint(str(tmp_path / "b")).endswith("step_4")

    # B2: restart — resumes from step_4 and stops at the SAME global step
    monkeypatch.delenv("TPU_FAULT_INJECT")
    reset_saved_state()
    logs2 = []
    state_b, _ = _tiny_lm(str(tmp_path / "b"), logs2.append, num_steps=7,
                          stop_at_step=8)
    assert any("resumed from" in l for l in logs2)
    assert int(state_b.step) == 8

    # token-identical: the step-keyed stream replayed exactly the batches
    # the uninterrupted run consumed, so params agree BITWISE
    for a, b in zip(jax.tree.leaves(state_a.params),
                    jax.tree.leaves(state_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# The pp divergence backstop: no in-step streak counter (1F1B computes
# grads in-schedule), so the loop reads the loss back every divergence_k
# steps and routes a non-finite value into the SAME rollback path.
# ---------------------------------------------------------------------------

class _BareState(struct.PyTreeNode):
    """PPTrainState-shaped: NO nonfinite_streak field — rollback must
    not assume the flat trainers' streak counter exists."""
    step: Any
    params: Any
    opt_state: Any


def test_rollback_handles_states_without_streak_field(tmp_path):
    good = _BareState(step=jnp.asarray(2, jnp.int32),
                      params={"w": jnp.full((4,), 2.0, jnp.float32)},
                      opt_state={"m": jnp.zeros((4,), jnp.float32)})
    save_checkpoint(tmp_path, good, step=2)
    ctx = ResilienceContext(ResilienceConfig(train_dir=str(tmp_path)),
                            log=lambda s: None)
    diverged = good.replace(
        step=jnp.asarray(9, jnp.int32),
        params={"w": jnp.full((4,), jnp.nan, jnp.float32)})
    rolled = ctx.rollback(diverged)
    assert int(rolled.step) == 2
    np.testing.assert_array_equal(np.asarray(rolled.params["w"]), 2.0)


def test_pp_benchmark_nonfinite_loss_rolls_back(tmp_path):
    import optax

    from mpi_operator_tpu.models.transformer import gpt2_config
    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.train import LMTrainerConfig, PipelineLMTrainer

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=128, max_len=16)
    mesh = make_mesh(MeshConfig(pp=2, dp=4))
    t = PipelineLMTrainer(cfg, mesh,
                          LMTrainerConfig(global_batch_size=16, seq_len=16,
                                          warmup_steps=1),
                          num_microbatches=4, tx=optax.sgd(0.1))
    state = t.init_state(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (16, 17), 0, 128)
    batch = t.microbatch(toks[:, :-1], toks[:, 1:])
    state, _ = t.train_step(state, *batch)
    save_checkpoint(str(tmp_path), state)       # the intact restore point
    # poison the live params: every loss is non-finite until rollback
    poisoned = state.replace(params=jax.tree.map(
        lambda x: jnp.full_like(x, jnp.nan), state.params))

    class Rep:
        def __iter__(self):
            return iter([batch] * 16)

    logs = []
    ctx = ResilienceContext(
        ResilienceConfig(train_dir=str(tmp_path), divergence_k=1,
                         max_rollbacks=2),
        log=logs.append)
    final, metrics = t.benchmark(poisoned, Rep(), num_steps=3,
                                 warmup_steps=1, log=logs.append,
                                 resilience=ctx)
    assert any("non-finite loss at step" in l for l in logs)
    assert any("divergence rollback #1" in l for l in logs)
    # rolled back once, then trained clean from the restored params
    assert not any("divergence rollback #2" in l for l in logs)
    assert np.isfinite(metrics["final_loss"])
    assert all(np.all(np.isfinite(np.asarray(x)))
               for x in jax.tree.leaves(final.params))
