"""Serving front door (serve/router.py) + SLO autoscaling policy
(controller/autoscale.py) + the burst scrape-fault modifier.

The contracts under test:

- **Keying parity**: the router's affinity score and the replica's
  prefix-cache admission lookup walk the SAME
  ``prefix_chain_windows`` keying (PageAllocator.probe vs .lookup) —
  probe depth k promises a later lookup at least k hit pages, so a
  keying change in slots.py can never silently diverge the two sides.
- **Load wins over warmth**: a replica at its in-flight cap is
  ineligible no matter how warm its cache is; when EVERY replica is at
  cap the request sheds at the front door with finish_reason "shed"
  and zero replica contact.
- **Failover idempotence**: a dead replica's in-flight requests replay
  to survivors; results key by id, the dead replica's partials are
  dropped, so the caller sees exactly one result per request.
- **Autoscale hysteresis**: breach persistence, clear persistence, and
  the resize-cost cooldown each independently veto a scale step;
  missing observations never breach and always block scale-down.
- **Burst schedule**: ``:burst:<period>/<duty>`` oscillates a rule
  deterministically over per-rank fetch counts, and every in-burst
  injection names its window index next to the seed.
- **Live topology steps**: a detach mid-drain FAILS OVER the drained
  replica's queued requests to survivors (never sheds them); an attach
  joins only a pre-warmed engine (the compile pin) and absorbs overload
  the incumbent fleet would have shed; dispatch prefers a fresh
  heartbeat report over in-process probing and falls back when stale.
"""
import pytest

from mpi_operator_tpu.api.types import ServingSLO, ServingSpec, TPUJobSpec
from mpi_operator_tpu.api.validation import ValidationError, validate_spec
from mpi_operator_tpu.controller.autoscale import (
    DecodeAutoscaler,
    SLOObservation,
)
from mpi_operator_tpu.serve import Request, Router, RouterConfig
from mpi_operator_tpu.serve.engine import RequestResult
from mpi_operator_tpu.serve.slots import PageAllocator, prefix_chain_windows
from mpi_operator_tpu.telemetry.chaos import (
    ScrapeFaultInjector,
    ScrapeFaultRule,
)


# ---------------------------------------------------------------------------
# keying parity: router-side probe vs replica-side lookup
# ---------------------------------------------------------------------------

def _publish_chain(alloc, prompt, pages=None):
    """Prefill-publish `prompt`'s complete pages the way the engine
    does: alloc, publish under the chain key, release to the LRU."""
    parent = -1
    for window in prefix_chain_windows(prompt, alloc.page_size, pages):
        key = (parent, window)
        page = alloc._cache.get(key)
        if page is None:
            page = alloc.alloc()
            assert alloc.publish(page, parent, window)
            alloc.release(page)
        parent = alloc._cache[key]


def test_probe_matches_lookup_depth_and_counters():
    alloc = PageAllocator(num_pages=17, page_size=4)
    prompt = list(range(1, 14))                # 13 tokens -> 3 full pages
    _publish_chain(alloc, prompt)
    assert alloc.probe(prompt) == 3
    # a longer prompt sharing the prefix probes the same warm depth
    assert alloc.probe(prompt + [99, 98, 97, 96, 95]) == 3
    # a prompt diverging inside the second page keeps only page one
    assert alloc.probe([1, 2, 3, 4, 99, 6, 7, 8, 9]) == 1
    # probe touched no counters and pinned nothing
    assert (alloc.hits, alloc.misses) == (0, 0)
    assert all(r == 0 for r in alloc.ref)
    # lookup walks the identical windows: depth equals the probe's
    # promise and the hit counter moves by exactly that many pages
    chain = alloc.lookup(prompt, full_pages=3)
    assert len(chain) == 3
    assert (alloc.hits, alloc.misses) == (3, 0)
    for p in chain:
        alloc.release(p)
    alloc.check()


def test_probe_and_lookup_share_window_source():
    # both sides key off prefix_chain_windows — publishing under those
    # windows (and ONLY those windows) is sufficient for both to match,
    # for assorted prompt lengths incl. the len-1 bonus-token edge
    alloc = PageAllocator(num_pages=33, page_size=8)
    for n in (1, 7, 8, 9, 16, 17, 31):
        prompt = [n * 100 + i for i in range(n)]
        windows = prefix_chain_windows(prompt, 8)
        assert len(windows) == max(0, (n - 1) // 8)
        _publish_chain(alloc, prompt)
        assert alloc.probe(prompt) == len(windows)


# ---------------------------------------------------------------------------
# routing policy over fake replicas (no jax)
# ---------------------------------------------------------------------------

class _FakeScheduler:
    def __init__(self):
        self.queue = []

    def next_arrival(self):
        return None


class _FakeSlots:
    def __init__(self, n):
        self.free = list(range(n))


class _FakeEngine:
    """Duck-typed stand-in for ServingEngine's steppable session
    surface: submitted requests retire after `service_ticks` ticks with
    a deterministic token, publishing their prompt pages like a real
    prefill would."""

    def __init__(self, slots=4, num_pages=65, page_size=8,
                 service_ticks=1):
        self.page_allocator = PageAllocator(num_pages, page_size)
        self.scheduler = _FakeScheduler()
        self.slots = _FakeSlots(slots)
        self.service_ticks = service_ticks
        self.submitted = []
        self._work = {}
        self._results = {}

    def start(self, on_token=None, now_fn=None):
        self._results = {}

    def submit(self, req):
        self.submitted.append(req.id)
        self._work[req.id] = [req, self.service_ticks]

    @property
    def active(self):
        return bool(self._work)

    def tick(self):
        if not self._work:
            return False
        for rid in list(self._work):
            self._work[rid][1] -= 1
            if self._work[rid][1] <= 0:
                req, _ = self._work.pop(rid)
                _publish_chain(self.page_allocator, req.prompt)
                self._results[rid] = RequestResult(
                    id=rid, tokens=[sum(req.prompt) % 97], logprobs=[],
                    finish_reason="eos", ttft=0.0, token_times=[0.0],
                    cached_tokens=0, admitted_at=0.0)
        return True

    def session_results(self):
        return self._results

    def finish(self):
        return self._results


def _req(rid, prompt, arrival=0.0):
    return Request(id=rid, prompt=list(prompt), max_new_tokens=4,
                   arrival=arrival)


def test_affinity_routes_to_warm_replica():
    fakes = [_FakeEngine(), _FakeEngine()]
    prefix = list(range(1, 17))                   # 2 full pages @ 8
    _publish_chain(fakes[1].page_allocator, prefix)
    router = Router(fakes, RouterConfig())
    rep = router._pick(_req(0, prefix + [50, 51]))
    assert rep.index == 1                         # warmth beats index 0
    # affinity off: pure load, tie -> lowest index
    router_off = Router([_FakeEngine(), _FakeEngine()],
                        RouterConfig(affinity=False))
    _publish_chain(router_off.replicas[1].engine.page_allocator, prefix)
    assert router_off._pick(_req(0, prefix + [50, 51])).index == 0


def test_affinity_never_overrides_full_replica():
    fakes = [_FakeEngine(), _FakeEngine()]
    prefix = list(range(1, 17))
    _publish_chain(fakes[0].page_allocator, prefix)
    router = Router(fakes, RouterConfig(max_inflight=1))
    router.replicas[0].inflight[999] = _req(999, [1, 2, 3])
    # replica 0 is warm but AT CAP: the load filter runs before any
    # affinity scoring, so the cold survivor gets the request
    assert router._pick(_req(0, prefix + [50])).index == 1


def test_shed_semantics_end_to_end():
    fakes = [_FakeEngine(), _FakeEngine()]
    router = Router(fakes, RouterConfig(max_inflight=1))
    reqs = [_req(i, [10 + i, 11 + i, 12 + i]) for i in range(5)]
    out = router.run(reqs)
    assert set(out) == {0, 1, 2, 3, 4}
    sheds = {rid for rid, r in out.items() if r.finish_reason == "shed"}
    assert len(sheds) == 3                        # 5 due at once, 2 caps
    for rid in sheds:
        assert out[rid].tokens == [] and out[rid].ttft == -1.0
        # a shed request never touched any replica
        assert all(rid not in f.submitted for f in fakes)
    assert router.shed_count() == 3
    assert sorted(router.dispatch_counts()) == [1, 1]


def test_span_too_large_is_not_a_candidate():
    fake = _FakeEngine(num_pages=3, page_size=8)   # usable = 2 pages
    router = Router([fake], RouterConfig())
    out = router.run([_req(0, list(range(40)))])   # span > 2 pages
    assert out[0].finish_reason == "shed"
    assert fake.submitted == []


def test_failover_resubmits_and_dedups():
    fakes = [_FakeEngine(service_ticks=3), _FakeEngine(service_ticks=3)]
    calls = {"n": 0}
    real_tick = fakes[0].tick

    def dying_tick():
        calls["n"] += 1
        if calls["n"] > 1:
            raise IOError("injected")
        return real_tick()

    fakes[0].tick = dying_tick
    router = Router(fakes, RouterConfig())
    reqs = [_req(i, [20 + i, 21 + i, 22 + i]) for i in range(4)]
    out = router.run(reqs)
    assert router.dead_replicas() == [0]
    assert router.resubmitted_total >= 1
    # exactly one result per id, all completed (nothing lost, nothing
    # duplicated), every replayed id reached the survivor
    assert set(out) == {0, 1, 2, 3}
    assert all(r.finish_reason == "eos" for r in out.values())
    # every id ultimately completed on the survivor
    assert set(fakes[1].submitted) == {0, 1, 2, 3}


def test_all_replicas_dead_raises():
    fakes = [_FakeEngine(), _FakeEngine()]
    for f in fakes:
        f.tick = lambda: (_ for _ in ()).throw(IOError("down"))
    router = Router(fakes, RouterConfig())
    with pytest.raises(RuntimeError, match="every replica died"):
        router.run([_req(0, [1, 2, 3])])


def test_duplicate_request_ids_rejected():
    router = Router([_FakeEngine()], RouterConfig())
    with pytest.raises(ValueError, match="duplicate request id"):
        router.run([_req(7, [1, 2]), _req(7, [3, 4])])


def test_router_config_validation():
    with pytest.raises(ValueError):
        Router([], RouterConfig())
    with pytest.raises(ValueError):
        Router([_FakeEngine()], RouterConfig(max_inflight=0))


# ---------------------------------------------------------------------------
# live topology: attach / detach / heartbeats (no jax)
# ---------------------------------------------------------------------------

class _QueueingEngine(_FakeEngine):
    """Fake with a real admission queue: submissions wait in
    scheduler.queue until a serving lane frees (`concurrent` at a
    time) — the queued-behind-slots state a graceful drain must pull
    back and fail over."""

    def __init__(self, concurrent=1, **kw):
        super().__init__(**kw)
        self.concurrent = concurrent

    def submit(self, req):
        self.submitted.append(req.id)
        self.scheduler.queue.append(req)

    @property
    def active(self):
        return bool(self._work or self.scheduler.queue)

    def tick(self):
        while self.scheduler.queue and len(self._work) < self.concurrent:
            req = self.scheduler.queue.pop(0)
            self._work[req.id] = [req, self.service_ticks]
        return super().tick()


class _WarmableEngine(_QueueingEngine):
    """Queueing fake that exposes compile_counts — the surface the
    attach warmup pin checks."""

    def __init__(self, step_compiles=1, **kw):
        super().__init__(**kw)
        self._step_compiles = step_compiles

    def compile_counts(self):
        return {"step": self._step_compiles, "prefill": 0}


def test_detach_mid_drain_fails_over_queued_requests():
    # scale-down RACING submission: four requests land, two queue behind
    # replica 0's single lane, then the drain begins — the queued ones
    # must fail over to the survivor (resubmit path), never shed, and
    # the resident one finishes in place on the draining replica
    fakes = [_QueueingEngine(service_ticks=2),
             _QueueingEngine(service_ticks=2)]
    router = Router(fakes, RouterConfig())
    orig_tick = fakes[0].tick
    fired = {"done": False}

    def tick_then_detach():
        r = orig_tick()
        if not fired["done"]:
            fired["done"] = True
            router.detach_replica(0)
        return r

    fakes[0].tick = tick_then_detach
    out = router.run([_req(i, [30 + i, 31 + i, 32 + i]) for i in range(4)])
    assert set(out) == {0, 1, 2, 3}
    assert all(r.finish_reason == "eos" for r in out.values())
    assert router.shed_count() == 0                 # failover, NOT shed
    assert router.resubmitted_total >= 1            # the pulled-back ones
    # a graceful exit is a detach, never a death
    assert router.detached_replicas() == [0]
    assert router.dead_replicas() == []
    assert router.active_count() == 1
    # the completed step landed in the live-scale log with its phase
    (entry,) = router.live_scale_log
    assert entry["action"] == "detach" and entry["replica"] == 0
    assert entry["drain_seconds"] >= 0.0
    assert entry["total_seconds"] == entry["drain_seconds"]
    # drained replica handed every page and slot back
    assert fakes[0].page_allocator.in_use == 0


def test_detach_verifies_reclaim_and_guards_last_replica():
    router = Router([_FakeEngine(), _FakeEngine()], RouterConfig())
    with pytest.raises(ValueError, match="no live replica"):
        router.detach_replica(7)
    router.replicas[1].alive = False
    with pytest.raises(ValueError, match="last active replica"):
        router.detach_replica(0)


def test_attach_requires_the_compile_pin():
    router = Router([_FakeEngine()], RouterConfig())
    with pytest.raises(ValueError, match="PRE-WARMED"):
        router.attach_replica(_WarmableEngine(step_compiles=0))
    # engines that don't expose compile_counts duck-pass; warmed pass
    router.attach_replica(_WarmableEngine(step_compiles=1))
    assert router.active_count() == 2


def test_attach_during_overload_absorbs_queue():
    # one replica, cap 2, four simultaneous arrivals: without the +1
    # step two requests shed at the front door (the
    # test_shed_semantics_end_to_end geometry). A pre-warmed attach at
    # t=0 absorbs the overflow instead — zero sheds, and the newcomer
    # never compiled anything new (its pin count is untouched).
    base = _QueueingEngine(service_ticks=2, concurrent=2)
    newcomer = _WarmableEngine(step_compiles=1, service_ticks=2,
                               concurrent=2)
    router = Router([base], RouterConfig(max_inflight=2))
    router.schedule_attach(0.0, newcomer, warmup_seconds=0.125)
    out = router.run([_req(i, [40 + i, 41 + i]) for i in range(4)])
    assert set(out) == {0, 1, 2, 3}
    assert router.shed_count() == 0
    assert all(r.finish_reason == "eos" for r in out.values())
    assert len(newcomer.submitted) == 2             # absorbed the overflow
    assert newcomer.compile_counts() == {"step": 1, "prefill": 0}
    (entry,) = router.live_scale_log
    assert entry["action"] == "attach"
    assert entry["warmup_seconds"] == 0.125
    assert entry["total_seconds"] == 0.125
    assert router.active_count() == 2


def test_heartbeat_preferred_until_stale():
    from mpi_operator_tpu.telemetry.worker import RouterTelemetry

    tel = RouterTelemetry()
    fakes = [_FakeEngine(), _FakeEngine()]
    router = Router(fakes, RouterConfig(affinity=False,
                                        heartbeat_interval=0.5),
                    telemetry=tel)
    # probing sees both replicas empty, but replica 0's PUBLISHED report
    # says it is buried — a fresh heartbeat must win over the probe
    tel.note_heartbeat(0, now=0.0, queue_depth=5, free_slots=0,
                       free_pages=0)
    tel.note_heartbeat(1, now=0.0, queue_depth=0, free_slots=4,
                       free_pages=64)
    assert router._pick(_req(0, [1, 2, 3]), now=0.2).index == 1
    # past the staleness threshold (2x interval) the report is dead
    # air: fall back to probing — a tie, so lowest index wins again
    assert router._pick(_req(1, [1, 2, 3]), now=5.0).index == 0
    # heartbeats off: the stored report is never consulted
    router_off = Router(fakes, RouterConfig(affinity=False), telemetry=tel)
    assert router_off._pick(_req(2, [1, 2, 3]), now=0.2).index == 0


# ---------------------------------------------------------------------------
# real-engine telemetry parity (jax)
# ---------------------------------------------------------------------------

@pytest.mark.serving
def test_affinity_hit_pages_match_replica_side_hits():
    # the router's predicted warm pages (probe at dispatch) must equal
    # the replicas' OWN prefix-cache hit counters (lookup at admission)
    # — the no-silent-divergence contract between router.py and
    # slots.py keying. Two rounds over the same fleet: round one plants
    # each tenant's pages on a distinct replica, round two re-serves
    # the tenants and every predicted page must cash in.
    import jax
    import jax.numpy as jnp
    from flax.core import meta as flax_meta
    from mpi_operator_tpu.models import CausalLM, gpt2_config
    from mpi_operator_tpu.serve import EngineConfig, ServingEngine

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=64)
    model = CausalLM(cfg)
    probe = jnp.zeros((1, 4), jnp.int32)
    params = flax_meta.unbox(
        model.init(jax.random.PRNGKey(0), probe))["params"]
    mk = lambda: ServingEngine(model, params, EngineConfig(  # noqa: E731
        slots=2, chunk_buckets=(8, 32), paged=True, page_size=8,
        rng_seed=0))
    engines = [mk(), mk()]
    # 17 tokens = 2 complete pages @ 8 (+1 bonus token outside paging)
    tenant_a = [(7 * i + 3) % 60 + 1 for i in range(17)]
    tenant_b = [(5 * i + 11) % 60 + 1 for i in range(17)]

    def round_trip(prompts):
        router = Router(engines, RouterConfig())
        out = router.run([Request(id=i, prompt=p, max_new_tokens=3,
                                  arrival=0.0)
                          for i, p in enumerate(prompts)])
        assert all(r.finish_reason in ("eos", "length")
                   for r in out.values())
        return router

    hits_before = sum(e.page_allocator.hits for e in engines)
    r1 = round_trip([tenant_a, tenant_b])
    assert r1.affinity_hit_pages == 0            # cold fleet: no warmth
    hits_mid = sum(e.page_allocator.hits for e in engines)
    assert hits_mid == hits_before               # ...and no cache hits
    # round two: same 2 full pages per tenant, fresh bonus tails
    r2 = round_trip([tenant_a[:16] + [61], tenant_b[:16] + [62]])
    hit_delta = sum(e.page_allocator.hits for e in engines) - hits_mid
    assert r2.affinity_hit_pages == hit_delta == 4
    assert r2.affinity_hit_rate() == 1.0
    # the warm rounds routed each tenant back to its planted replica
    assert sorted(r2.dispatch_counts()) == [1, 1]


# ---------------------------------------------------------------------------
# DecodeAutoscaler policy (pure)
# ---------------------------------------------------------------------------

def _slo(**kw):
    base = dict(ttft_p99_seconds=1.0, min_decode_replicas=1,
                max_decode_replicas=8, breach_seconds=30.0,
                clear_seconds=60.0, cooldown_multiplier=4.0,
                cooldown_floor_seconds=10.0)
    base.update(kw)
    return ServingSLO(**base)


def test_breach_must_persist_before_scale_up():
    sc = DecodeAutoscaler(_slo())
    bad = SLOObservation(ttft_p99=2.0)
    d = sc.decide(100.0, bad, current=2, last_scaled_at=None,
                  last_resize_seconds=None)
    assert d.target is None and d.wake_after == pytest.approx(30.0)
    d = sc.decide(115.0, bad, current=2, last_scaled_at=None,
                  last_resize_seconds=None)
    assert d.target is None                      # held 15s < 30s
    d = sc.decide(130.0, bad, current=2, last_scaled_at=None,
                  last_resize_seconds=None)
    assert d.target == 3 and "ttft_p99" in d.reason


def test_one_good_scrape_resets_the_breach_timer():
    sc = DecodeAutoscaler(_slo())
    bad, good = SLOObservation(ttft_p99=2.0), SLOObservation(ttft_p99=0.5)
    sc.decide(0.0, bad, 2, None, None)
    sc.decide(20.0, good, 2, None, None)         # breach clears
    d = sc.decide(25.0, bad, 2, None, None)      # a NEW breach window
    assert d.target is None
    d = sc.decide(54.0, bad, 2, None, None)
    assert d.target is None                      # 29s into the new window
    assert sc.decide(55.0, bad, 2, None, None).target == 3


def test_cooldown_scales_with_measured_resize_cost():
    sc = DecodeAutoscaler(_slo())
    assert sc.cooldown_seconds(None) == 10.0     # floor until measured
    assert sc.cooldown_seconds(90.0) == 360.0    # 4 x the gang resize
    bad = SLOObservation(ttft_p99=2.0)
    sc.decide(0.0, bad, 2, None, 90.0)
    d = sc.decide(40.0, bad, 2, last_scaled_at=35.0,
                  last_resize_seconds=90.0)
    assert d.target is None and "cooling" in d.reason
    assert d.wake_after == pytest.approx(355.0)
    d = sc.decide(35.0 + 360.0, bad, 2, last_scaled_at=35.0,
                  last_resize_seconds=90.0)
    assert d.target == 3


def test_scale_up_clamped_at_max():
    sc = DecodeAutoscaler(_slo(max_decode_replicas=2))
    bad = SLOObservation(ttft_p99=2.0)
    sc.decide(0.0, bad, 2, None, None)
    d = sc.decide(31.0, bad, 2, None, None)
    assert d.target is None and "maxDecodeReplicas" in d.reason


def test_missing_observation_never_breaches_and_blocks_clear():
    sc = DecodeAutoscaler(_slo())
    dark = SLOObservation()                      # no data at all
    d = sc.decide(0.0, dark, 2, None, None)
    assert d.target is None and "insufficient" in d.reason
    # an hour of darkness still never scales in either direction
    d = sc.decide(3600.0, dark, 2, None, None)
    assert d.target is None


def test_partial_evidence_blocks_scale_down():
    sc = DecodeAutoscaler(_slo(tpot_p99_seconds=0.1))
    # ttft observed and clear, tpot configured but dark -> hold
    d = sc.decide(0.0, SLOObservation(ttft_p99=0.2), 3, None, None)
    assert d.target is None and "insufficient" in d.reason


def test_clear_must_persist_then_scales_down():
    sc = DecodeAutoscaler(_slo())
    good = SLOObservation(ttft_p99=0.2)
    d = sc.decide(0.0, good, 3, None, None)
    assert d.target is None and d.wake_after == pytest.approx(60.0)
    d = sc.decide(59.0, good, 3, None, None)
    assert d.target is None
    d = sc.decide(61.0, good, 3, None, None)
    assert d.target == 2


def test_scale_down_clamped_at_min():
    sc = DecodeAutoscaler(_slo())
    good = SLOObservation(ttft_p99=0.2)
    for t in (0.0, 61.0, 200.0):
        assert sc.decide(t, good, 1, None, None).target is None


def test_queue_depth_target_breaches():
    sc = DecodeAutoscaler(_slo(ttft_p99_seconds=None, queue_depth=4.0))
    deep = SLOObservation(queue_depth=9.0)
    sc.decide(0.0, deep, 2, None, None)
    d = sc.decide(30.0, deep, 2, None, None)
    assert d.target == 3 and "queue_depth" in d.reason


# ---------------------------------------------------------------------------
# spec.serving.slo validation
# ---------------------------------------------------------------------------

def _serving_spec(slo):
    return TPUJobSpec(tpus=8, serving=ServingSpec(
        prefill_replicas=1, decode_replicas=1, slo=slo))


def test_slo_validation():
    validate_spec(_serving_spec(ServingSLO(ttft_p99_seconds=0.5)))
    with pytest.raises(ValidationError, match="at least one target"):
        validate_spec(_serving_spec(ServingSLO()))
    with pytest.raises(ValidationError, match="must be > 0"):
        validate_spec(_serving_spec(ServingSLO(ttft_p99_seconds=-1.0)))
    with pytest.raises(ValidationError, match="maxDecodeReplicas"):
        validate_spec(_serving_spec(ServingSLO(
            ttft_p99_seconds=0.5, min_decode_replicas=4,
            max_decode_replicas=2)))
    with pytest.raises(ValidationError, match="inside the slo band"):
        validate_spec(_serving_spec(ServingSLO(
            ttft_p99_seconds=0.5, min_decode_replicas=2,
            max_decode_replicas=4)))
    with pytest.raises(ValidationError, match="breachSeconds"):
        validate_spec(_serving_spec(ServingSLO(
            ttft_p99_seconds=0.5, breach_seconds=-1.0)))


# ---------------------------------------------------------------------------
# burst scrape-fault schedule
# ---------------------------------------------------------------------------

def test_burst_rule_parse_and_validation():
    r = ScrapeFaultRule.parse("*/fail=0.6:burst:8/0.25")
    assert (r.rate, r.burst_period, r.burst_duty) == (0.6, 8, 0.25)
    assert ScrapeFaultRule.parse("3/delay=0.2").burst_period is None
    for bad in ("*/fail=0.5:burst:8", "*/fail=0.5:burst:x/0.5",
                "*/fail=0.5:gust:8/0.5"):
        with pytest.raises(ValueError):
            ScrapeFaultRule.parse(bad)
    with pytest.raises(ValueError, match="duty"):
        ScrapeFaultRule.parse("*/fail=0.5:burst:8/1.0")
    with pytest.raises(ValueError, match="period"):
        ScrapeFaultRule.parse("*/fail=0.5:burst:1/0.5")


def test_burst_phasing_is_a_square_wave():
    r = ScrapeFaultRule.parse("*/fail=1.0:burst:4/0.5")
    assert [r.live(i) for i in range(8)] == [True, True, False, False,
                                             True, True, False, False]
    assert [r.burst_index(i) for i in range(8)] == [0, 0, 0, 0,
                                                    1, 1, 1, 1]


def test_burst_messages_name_their_window():
    inj = ScrapeFaultInjector(["*/fail=1.0:burst:4/0.5"], seed=9)
    seen = []
    for i in range(8):
        try:
            inj.fetch(0, "u", lambda u: "ok")
            seen.append(None)
        except IOError as exc:
            seen.append(str(exc))
    assert seen[0] and "(seed=9, burst=0)" in seen[0]
    assert seen[4] and "(seed=9, burst=1)" in seen[4]
    assert seen[2] is None and seen[3] is None      # silent phase
    assert inj.burst_windows_hit() == 2
    # static rules keep the bare seed tag (no burst index)
    inj2 = ScrapeFaultInjector(["*/fail=1.0"], seed=9)
    with pytest.raises(IOError, match=r"\(seed=9\)$"):
        inj2.fetch(0, "u", lambda u: "ok")


def test_burst_schedule_is_deterministic_per_seed():
    def seq(seed):
        inj = ScrapeFaultInjector(["*/fail=0.5:burst:4/0.5"], seed=seed)
        out = []
        for i in range(32):
            try:
                inj.fetch(0, "u", lambda u: "ok")
                out.append("ok")
            except IOError as exc:
                out.append(str(exc))
        return out

    assert seq(3) == seq(3)
    assert seq(3) != seq(4)


def test_burst_silent_phase_rolls_no_randomness():
    # a second, always-live rule must see the SAME roll stream whether
    # the burst rule is in its storm or its calm — the burst phase is
    # decided by counters, never by consuming rng
    rules = ["0/fail=1.0:burst:2/0.4", "*/delay=0.0000001"]
    inj = ScrapeFaultInjector(rules, seed=5)
    # rank 1 never matches the burst rule; its delay rolls come straight
    # off the shared rng in fetch order regardless of rank 0's phase
    for i in range(6):
        inj.fetch(1, "u1", lambda u: "ok")
    assert inj.fault_count("delay") == 0


# ---------------------------------------------------------------------------
# controller integration: status-override scale-up
# ---------------------------------------------------------------------------

def test_autoscale_scale_up_lands_in_status_and_pools():
    from mpi_operator_tpu.controller.chaos import _observed_harness

    qd = {"v": 0.0}

    def fetch(url):
        if url.endswith("/metrics"):
            return f"tpu_worker_queue_depth {qd['v']}\n"
        raise IOError("no events endpoint")

    h, obs, clock = _observed_harness(0, fetch)
    # the autoscaler's persistence windows read controller time; pin it
    # to the same fake clock the observatory scrapes on
    h.controller.now = lambda: clock["now"]
    name = "as-up"
    slo = ServingSLO(queue_depth=4.0, breach_seconds=30.0,
                     clear_seconds=600.0, cooldown_floor_seconds=0.0,
                     max_decode_replicas=4)
    h.create_job(name, tpus=8, serving=ServingSpec(
        prefill_replicas=1, decode_replicas=1, slo=slo))
    h.drive_until(lambda: len(h.worker_sets(name)) == 2,
                  f"{name}: prefill+decode pools")
    h.make_workers_ready(name)
    h.drive_until(lambda: h.launcher(name) is not None, f"{name}: launcher")
    h.set_launcher_active(name)
    h.drive_until(lambda: h.cond(name, "Running") == "True",
                  f"{name}: Running")
    sync = lambda: h.controller.sync_handler(f"{h.ns}/{name}")  # noqa: E731
    # healthy queue: no override appears no matter how long we watch
    for _ in range(4):
        clock["now"] += 15
        sync()
        h.resync()
    assert h.job(name).status.serving_decode_replicas is None
    # the queue blows past the target and STAYS there past breachSeconds
    qd["v"] = 9.0
    for _ in range(4):
        clock["now"] += 15
        sync()
        h.resync()
    job = h.job(name)
    assert job.status.serving_decode_replicas == 2
    assert job.status.serving_scaled_at is not None
    # the override flows into the decode pool via the ordinary resize
    # machinery: the user's spec is untouched, the StatefulSet grows
    assert job.spec.serving.decode_replicas == 1
    h.drive_until(lambda: any(
        s.metadata.name.endswith("-decode") and s.spec.replicas == 2
        for s in h.worker_sets(name)), f"{name}: decode pool resized")
