"""Fleet scheduler tests: the pure policy (controller/scheduler.py),
the controller glue (_sched_reconcile and friends), the serialized
surface, and the postmortem's scheduler-actions section.

Policy tests exercise FleetScheduler directly — it is a deterministic
function of (now, fleet view), no cluster needed. Glue tests reuse the
test_controller.py fixture idiom: InMemoryAPIServer as both tracker and
informer source, sync_handler called synchronously.
"""
import copy
import io
import time

import pytest

from mpi_operator_tpu.api import types as api
from mpi_operator_tpu.api.types import (
    Container, ObjectMeta, PodTemplateSpec, TPUJob, TPUJobSpec,
)
from mpi_operator_tpu.api.validation import ValidationError, validate_spec
from mpi_operator_tpu.cluster.apiserver import InMemoryAPIServer
from mpi_operator_tpu.cluster.serialize import from_manifest, to_manifest
from mpi_operator_tpu.controller import ControllerConfig, TPUJobController
from mpi_operator_tpu.controller.controller import WORKER_SUFFIX
from mpi_operator_tpu.controller.scheduler import (
    FleetScheduler, SchedDecision, SchedJob, ledger_cost,
)
from mpi_operator_tpu import postmortem
from mpi_operator_tpu.telemetry import events as ev


# ---------------------------------------------------------------------------
# fixture (same shape as test_controller.py's)
# ---------------------------------------------------------------------------

class Fixture:
    def __init__(self, **config_kwargs):
        self.api = InMemoryAPIServer()
        self.controller = TPUJobController(
            self.api, config=ControllerConfig(**config_kwargs)
        )
        self.controller.factory.start_all()

    def seed(self, obj):
        return self.api.create(obj)

    def run(self, key):
        self.api.clear_actions()
        self.controller.sync_handler(key)
        return self.api.write_actions()

    def job(self, name):
        return self.api.get(api.KIND, "default", name)

    def worker_set(self, name):
        return self.api.try_get("StatefulSet", "default",
                                name + WORKER_SUFFIX)

    def cond(self, name, ctype):
        return self.job(name).status.get_condition(ctype)


def new_job(name="test", tpus=8, **kw) -> TPUJob:
    spec = TPUJobSpec(
        tpus=tpus,
        template=PodTemplateSpec(
            containers=[Container(name="train", image="tpu-bench:latest")]
        ),
        **kw,
    )
    return TPUJob(metadata=ObjectMeta(name=name, namespace="default"),
                  spec=spec)


class FakeObservatory:
    """The two observatory surfaces the scheduler glue touches, recorded
    raw (the real note_sched dedup is collector.py's concern)."""

    def __init__(self, dark=frozenset(), total=0):
        self.sched = []
        self._dark = set(dark)
        self._total = total

    def merged_records(self, job):
        return []

    def __getattr__(self, name):
        # the glue calls many note_* hooks; only note_sched matters here
        if name.startswith("note_"):
            return lambda *a, **k: None
        raise AttributeError(name)

    def partition_state(self, job):
        return set(self._dark), self._total

    def note_sched(self, job, event, token, **fields):
        self.sched.append({"job": job, "event": event, "token": token,
                           **fields})


# ---------------------------------------------------------------------------
# ledger_cost: incomplete resize entries fall back, never KeyError/zero
# ---------------------------------------------------------------------------

def test_ledger_cost_empty_ledger_uses_default():
    assert ledger_cost([], 60.0) == 60.0


def test_ledger_cost_skips_incomplete_entries():
    # a crash mid-drain leaves entries with NO total_seconds (and a
    # died-before-resume one with total 0 is equally unusable): the read
    # must fall back to the newest MEASURED total, never raise, never
    # return zero
    resizes = [
        {"ts": 1.0, "total_seconds": 42.0},
        {"ts": 2.0, "drain_seconds": 3.0},          # crashed mid-resize
        {"ts": 3.0, "total_seconds": 0},            # degenerate
        {"ts": 4.0},                                # nothing measured
    ]
    assert ledger_cost(resizes, 60.0) == 42.0


def test_ledger_cost_newest_measured_wins():
    resizes = [{"total_seconds": 10.0}, {"total_seconds": 99.0}]
    assert ledger_cost(resizes, 60.0) == 99.0


def test_ledger_cost_all_incomplete_uses_default():
    resizes = [{"drain_seconds": 1.0}, {"restore_seconds": 2.0}]
    assert ledger_cost(resizes, 7.5) == 7.5


# ---------------------------------------------------------------------------
# policy: admission order + strict head-of-line
# ---------------------------------------------------------------------------

def _sched(pool=8, floor=0.0, mult=4.0):
    return FleetScheduler(pool_chips=pool, cooldown_floor_seconds=floor,
                          cooldown_multiplier=mult)


def test_admission_descending_priority_then_creation_order():
    s = _sched(pool=8)
    jobs = [
        SchedJob(name="d/old-low", priority=0, created=1.0, chips=2,
                 pending=True),
        SchedJob(name="d/young-high", priority=2, created=9.0, chips=2,
                 pending=True),
        SchedJob(name="d/old-high", priority=2, created=5.0, chips=2,
                 pending=True),
    ]
    plan = s.plan(100.0, jobs)
    assert [n for n, _ in plan.admit] == [
        "d/old-high", "d/young-high", "d/old-low"]
    assert plan.hold == []
    assert plan.action is None


def test_strict_head_of_line_no_backfill():
    # the blocked high-priority claim must not be starved by a stream of
    # small low-priority arrivals that WOULD fit
    s = _sched(pool=8)
    jobs = [
        SchedJob(name="d/running", chips=8, held_chips=8),
        SchedJob(name="d/big-high", priority=2, created=1.0, chips=8,
                 pending=True, queued_since=90.0),
        SchedJob(name="d/small-low", priority=0, created=2.0, chips=2,
                 pending=True),
    ]
    plan = s.plan(100.0, jobs)
    assert plan.admit == []
    holds = dict(plan.hold)
    assert "needs 8 chips" in holds["d/big-high"]
    assert holds["d/small-low"] == "behind d/big-high"


# ---------------------------------------------------------------------------
# policy: preempt victim selection + ladder target
# ---------------------------------------------------------------------------

def test_preempt_picks_lowest_priority_then_youngest_victim():
    s = _sched(pool=8)
    jobs = [
        SchedJob(name="d/old-low", priority=0, created=1.0, chips=4,
                 held_chips=4, elastic=True, shrink_ladder=(2, 1)),
        SchedJob(name="d/young-low", priority=0, created=5.0, chips=4,
                 held_chips=4, elastic=True, shrink_ladder=(2, 1)),
        SchedJob(name="d/hi", priority=1, chips=2, pending=True,
                 queued_since=0.0),
    ]
    plan = s.plan(100.0, jobs)
    assert plan.action is not None and plan.action.action == "preempt"
    assert plan.action.victim == "d/young-low"   # newest claim yields


def test_preempt_takes_largest_ladder_target_that_frees_enough():
    s = _sched(pool=8)
    jobs = [
        SchedJob(name="d/lo", priority=0, chips=8, held_chips=8,
                 elastic=True, shrink_ladder=(4, 2, 1)),
        SchedJob(name="d/hi", priority=1, chips=4, pending=True,
                 queued_since=0.0),
    ]
    plan = s.plan(100.0, jobs)
    d = plan.action
    assert d.action == "preempt" and d.to_chips == 4   # not 2, not 1


def test_preempt_never_targets_nonelastic_equal_priority_or_preempted():
    # pool exactly full (7 held of 7) so the already-shrunk job cannot
    # grow back either — the pass must end with NO action at all
    s = _sched(pool=7)
    jobs = [
        SchedJob(name="d/rigid", priority=0, chips=3, held_chips=3),
        SchedJob(name="d/peer", priority=1, chips=3, held_chips=3,
                 elastic=True, shrink_ladder=(1,)),
        SchedJob(name="d/shrunk", priority=0, chips=2, held_chips=1,
                 elastic=True, shrink_ladder=(1,), sched_tpus=1,
                 sched_scaled_at=0.0, preempt_beneficiary="d/other"),
        SchedJob(name="d/hi", priority=1, chips=4, pending=True,
                 queued_since=0.0),
    ]
    plan = s.plan(100.0, jobs)
    assert plan.action is None
    skips = [d for d in plan.skips if d.beneficiary == "d/hi"]
    assert skips and "no viable victim" in skips[0].reason


# ---------------------------------------------------------------------------
# policy: the cost gate (anti-thrash) and the cooldown brake
# ---------------------------------------------------------------------------

def test_cost_gate_declines_until_wait_pays_for_resize():
    # victim's last measured resize cost 100s, beneficiary queued 5s ago:
    # reclaimable slice-time < ledger cost -> explicit skip with the
    # evidence, wake armed for the crossover point
    s = _sched(pool=8, floor=0.0)
    jobs = [
        SchedJob(name="d/lo", priority=0, chips=8, held_chips=8,
                 elastic=True, shrink_ladder=(4,),
                 last_resize_seconds=100.0),
        SchedJob(name="d/hi", priority=1, chips=4, pending=True,
                 queued_since=95.0),
    ]
    plan = s.plan(100.0, jobs)
    assert plan.action is None
    d = plan.skips[0]
    assert d.action == "skip"
    assert d.predicted_cost_seconds == 100.0
    assert d.reclaim_seconds == 5.0
    assert d.wake_after == pytest.approx(95.0)
    assert plan.wake_after == pytest.approx(95.0)
    # ...and the admission is only DELAYED: once the wait crosses the
    # predicted cost the same fleet state preempts
    plan2 = s.plan(200.0, jobs)
    assert plan2.action is not None and plan2.action.action == "preempt"


def test_cooldown_brake_multiplies_last_measured_cost():
    s = _sched(pool=8, floor=10.0, mult=4.0)
    assert s.cooldown_seconds(None) == 10.0       # floor until measured
    assert s.cooldown_seconds(1.0) == 10.0        # never below the floor
    assert s.cooldown_seconds(50.0) == 200.0


def test_recently_scaled_victim_cools_down_with_wake():
    s = _sched(pool=8, floor=60.0)
    jobs = [
        # grew back at t=90 (sched_tpus cleared, stamp remains): the
        # brake must hold a re-preempt until the cooldown elapses
        SchedJob(name="d/lo", priority=0, chips=8, held_chips=8,
                 elastic=True, shrink_ladder=(4,), sched_scaled_at=90.0),
        SchedJob(name="d/hi", priority=1, chips=4, pending=True,
                 queued_since=0.0),
    ]
    plan = s.plan(100.0, jobs)
    assert plan.action is None
    d = plan.skips[0]
    assert "cooling down" in d.reason
    assert d.wake_after == pytest.approx(50.0)


def test_grow_back_when_pool_frees_and_at_most_one_action_per_pass():
    s = _sched(pool=8, floor=0.0)
    shrunk = SchedJob(name="d/lo", priority=0, chips=8, held_chips=4,
                      elastic=True, sched_tpus=4, sched_scaled_at=0.0,
                      preempt_beneficiary="d/hi")
    # pool still tight: no decision, no timer (a capacity release
    # resyncs the victim anyway)
    tight = SchedJob(name="d/hi", priority=1, chips=4, held_chips=4)
    plan = s.plan(100.0, [shrunk, tight])
    assert plan.action is None and plan.skips == []
    # beneficiary done -> grow back; and even with another pending job
    # blocked, the pass emits AT MOST ONE action
    done = SchedJob(name="d/hi", priority=1, chips=4, done=True)
    plan = s.plan(100.0, [shrunk, done])
    d = plan.action
    assert d.action == "grow_back"
    assert (d.from_chips, d.to_chips) == (4, 8)


def test_grow_back_respects_cooldown():
    s = _sched(pool=8, floor=60.0)
    shrunk = SchedJob(name="d/lo", priority=0, chips=8, held_chips=4,
                      elastic=True, sched_tpus=4, sched_scaled_at=70.0)
    plan = s.plan(100.0, [shrunk])
    assert plan.action is None
    assert plan.skips[0].wake_after == pytest.approx(30.0)


# ---------------------------------------------------------------------------
# policy: degraded-rank migration gate
# ---------------------------------------------------------------------------

def test_migration_once_per_window_and_cost_floor():
    s = _sched(pool=8, floor=60.0)
    early = s.migration(100.0, window_age=10.0, already_migrated=False)
    assert early.action == "skip"
    assert early.wake_after == pytest.approx(50.0)
    ripe = s.migration(100.0, window_age=75.0, already_migrated=False)
    assert ripe.action == "migrate"
    again = s.migration(100.0, window_age=200.0, already_migrated=True)
    assert again.action == "skip"
    assert "already migrated" in again.reason


# ---------------------------------------------------------------------------
# glue: admission + hold through the controller
# ---------------------------------------------------------------------------

def test_first_job_admitted_and_stamped_then_second_held():
    f = Fixture(sched_pool_chips=8, sched_cooldown_floor_seconds=0.0)
    f.seed(new_job("lo", tpus=8))
    f.run("default/lo")
    qc = f.cond("lo", api.COND_QUEUED)
    assert qc is not None and qc.status == "False"
    assert f.worker_set("lo") is not None
    # a rigid second job cannot fit and cannot preempt: held with a
    # Queued condition and ZERO resources created
    f.seed(new_job("hi", tpus=8, priority=1))
    actions = f.run("default/hi")
    qc = f.cond("hi", api.COND_QUEUED)
    assert qc is not None and qc.status == "True"
    assert qc.reason == "SchedQueued"
    assert f.worker_set("hi") is None
    assert all(a.verb == "update-status" for a in actions)


def test_preempt_to_admit_then_grow_back_end_to_end():
    f = Fixture(sched_pool_chips=8, sched_cooldown_floor_seconds=0.0)
    f.seed(new_job("lo", tpus=8, elastic=True, min_tpus=2))
    f.run("default/lo")
    assert f.worker_set("lo").spec.replicas == 2

    # hi arrives: its own sync queues it AND executes the preempt as a
    # guarded cross-job status write on lo
    f.seed(new_job("hi", tpus=4, priority=1))
    f.run("default/hi")
    lo = f.job("lo")
    assert lo.status.sched_tpus == 4
    pc = lo.status.get_condition(api.COND_PREEMPTED)
    assert pc is not None and pc.status == "True"
    assert "for=default/hi" in pc.message

    # hi's replan (the self re-enqueue) admits it into the freed chips
    f.run("default/hi")
    qc = f.cond("hi", api.COND_QUEUED)
    assert qc is not None and qc.status == "False"
    assert "via preempt" in qc.message
    assert f.worker_set("hi") is not None

    # lo's next sync materializes the shrink (2 -> 1 worker)
    f.run("default/lo")
    assert f.worker_set("lo").spec.replicas == 1

    # hi completes; lo's sync grows it back and rescales the same pass
    hi = f.job("hi")
    hi.status.set_condition(api.JobCondition(
        api.COND_SUCCEEDED, "True", "JobSucceeded", "done"))
    f.api.update_status(hi)
    f.run("default/lo")
    lo = f.job("lo")
    assert lo.status.sched_tpus is None
    pc = lo.status.get_condition(api.COND_PREEMPTED)
    assert pc is not None and pc.status == "False"
    assert f.worker_set("lo").spec.replicas == 2


def test_preempt_victim_guard_blocks_double_shrink():
    # the crash-replay guard: a victim that ALREADY carries a scheduler
    # override is never written again, whatever the decision says
    f = Fixture(sched_pool_chips=8, sched_cooldown_floor_seconds=0.0)
    f.seed(new_job("lo", tpus=8, elastic=True, min_tpus=2))
    f.run("default/lo")
    lo = f.job("lo")
    lo.status.sched_tpus = 4
    f.api.update_status(lo)
    f.api.clear_actions()
    f.controller._preempt_victim(SchedDecision(
        action="preempt", victim="default/lo", beneficiary="default/x",
        from_chips=8, to_chips=2, predicted_cost_seconds=0.0,
        reclaim_seconds=1.0))
    assert f.api.write_actions() == []
    assert f.job("lo").status.sched_tpus == 4          # unchanged


def test_anti_thrash_pin_holds_admission_and_records_skip():
    # floor >> any accrued wait: the gate must DECLINE (hi stays Queued,
    # lo untouched) and leave an explicit sched_skip with the evidence —
    # never a resize
    f = Fixture(sched_pool_chips=8,
                sched_cooldown_floor_seconds=3600.0)
    obs = FakeObservatory()
    f.controller.observatory = obs
    f.seed(new_job("lo", tpus=8, elastic=True, min_tpus=2))
    f.run("default/lo")
    f.seed(new_job("hi", tpus=4, priority=1))
    f.run("default/hi")
    f.run("default/hi")
    assert f.job("lo").status.sched_tpus is None
    assert f.worker_set("lo").spec.replicas == 2
    qc = f.cond("hi", api.COND_QUEUED)
    assert qc is not None and qc.status == "True"
    skips = [r for r in obs.sched if r["event"] == "sched_skip"
             and r["job"] == "hi"]
    assert skips
    assert skips[0]["predicted_cost_seconds"] == 3600.0
    assert skips[0]["reclaim_seconds"] < 3600.0


# ---------------------------------------------------------------------------
# glue: degraded-rank migration (status-first, once per window)
# ---------------------------------------------------------------------------

def _degraded_fixture(floor=0.0):
    f = Fixture(sched_cooldown_floor_seconds=floor)
    f.seed(new_job("mig", tpus=8, restart_policy="OnFailure"))
    f.run("default/mig")
    obs = FakeObservatory(dark={0}, total=2)
    f.controller.observatory = obs
    job = f.job("mig")
    job.status.set_condition(api.JobCondition(
        api.COND_DEGRADED_GANG, "True", "PartialPartition",
        "rank 0 unreachable, progress still observed"))
    job = f.api.update_status(job)
    alloc = f.controller.allocate_processing_units(job, False)
    return f, obs, job, alloc


def test_migration_deletes_dark_pod_once_per_window():
    f, obs, job, alloc = _degraded_fixture(floor=0.0)
    pod_names = f.controller.worker_pod_names(job, alloc)
    job = f.controller._sched_migrate_reconcile(job, alloc, "default/mig")
    assert job.status.migration_count == 1
    window = job.status.migrated_window
    assert window is not None and window.endswith(pod_names[0])
    migs = [r for r in obs.sched if r["event"] == "sched_migrate"]
    assert len(migs) == 1 and migs[0]["rank"] == 0
    # distinct from gang restarts: the restart counter never moved
    assert f.job("mig").status.restart_count == 0
    # replayed sync (same window): marker matches -> no second count
    replay = f.job("mig")
    replay = f.controller._sched_migrate_reconcile(
        replay, alloc, "default/mig")
    assert replay.status.migration_count == 1
    assert replay.status.migrated_window == window


def test_migration_skipped_below_cost_floor():
    f, obs, job, alloc = _degraded_fixture(floor=3600.0)
    job = f.controller._sched_migrate_reconcile(job, alloc, "default/mig")
    assert job.status.migration_count == 0
    assert job.status.migrated_window is None
    skips = [r for r in obs.sched if r["event"] == "sched_skip"]
    assert skips and "has not yet paid" in skips[0]["reason"]


def test_migration_ignores_total_partition():
    # every rank dark is a dead gang, not a partition — the restart
    # path owns it, the migration hook must not touch a pod
    f, obs, job, alloc = _degraded_fixture(floor=0.0)
    obs._dark = {0, 1}
    job = f.controller._sched_migrate_reconcile(job, alloc, "default/mig")
    assert job.status.migration_count == 0
    assert obs.sched == []


# ---------------------------------------------------------------------------
# serialized surface + admission validation
# ---------------------------------------------------------------------------

def test_priority_and_sched_status_round_trip():
    job = new_job("rt", tpus=8, priority=3)
    job.status.sched_tpus = 4
    job.status.sched_scaled_at = 1700000000.0
    job.status.migration_count = 2
    job.status.migrated_window = "1700000000.000:uid-9"
    back = from_manifest(to_manifest(job))
    assert back.spec.priority == 3
    assert back.status.sched_tpus == 4
    assert back.status.sched_scaled_at == pytest.approx(1700000000.0)
    assert back.status.migration_count == 2
    assert back.status.migrated_window == "1700000000.000:uid-9"
    # default priority serializes away entirely
    assert "priority" not in to_manifest(new_job("d"))["spec"]


@pytest.mark.parametrize("bad", [-1, True, 1.5, "2"])
def test_priority_validation_rejects_non_nonnegative_int(bad):
    job = new_job("bad", tpus=8)
    job.spec.priority = bad
    with pytest.raises(ValidationError, match="priority"):
        validate_spec(job.spec)


def test_priority_validation_accepts_zero_and_positive():
    for ok in (0, 7):
        job = new_job("ok", tpus=8, priority=ok)
        validate_spec(job.spec)


# ---------------------------------------------------------------------------
# postmortem: the "scheduler actions:" section
# ---------------------------------------------------------------------------

def _sched_timeline():
    return [
        {"ts": 100.0, "event": ev.JOB_CREATED, "host": "c", "job": "d/lo"},
        {"ts": 101.0, "event": ev.SCHED_QUEUE, "host": "c", "job": "d/hi",
         "reason": "waiting for 4 chips", "priority": 1},
        {"ts": 110.0, "event": ev.SCHED_PREEMPT, "host": "c",
         "job": "d/lo", "victim": "d/lo", "beneficiary": "d/hi",
         "from_tpus": 8, "to_tpus": 4, "predicted_cost_seconds": 60.0},
        {"ts": 112.0, "event": ev.GANG_RESIZE, "host": "c", "job": "d/lo",
         "tpus": 4},
        {"ts": 154.0, "event": ev.FIRST_RESUME_STEP, "host": "w",
         "job": "d/lo", "seconds": 39.0, "step": 12},
        {"ts": 116.0, "event": ev.SCHED_ADMIT, "host": "c", "job": "d/hi",
         "via": "preempt", "waited_seconds": 15.0},
        {"ts": 300.0, "event": ev.SCHED_SKIP, "host": "c", "job": "d/h2",
         "reason": "queued wait 4s has not yet paid for 42s",
         "predicted_cost_seconds": 42.0, "reclaim_seconds": 4.0},
        {"ts": 400.0, "event": ev.SCHED_GROW_BACK, "host": "c",
         "job": "d/lo", "from_tpus": 4, "to_tpus": 8},
        {"ts": 500.0, "event": ev.SCHED_MIGRATE, "host": "c",
         "job": "d/lo", "rank": 0, "pod": "lo-worker-0",
         "migration_count": 1, "window_age_seconds": 75.0},
        {"ts": 600.0, "event": ev.JOB_SUCCEEDED, "host": "c",
         "job": "d/lo"},
    ]


def test_postmortem_pairs_predicted_with_measured_cost():
    records = sorted(_sched_timeline(), key=lambda r: r["ts"])
    summary = postmortem.summarize(records)
    actions = summary["scheduler_actions"]
    assert [a["event"] for a in actions] == [
        ev.SCHED_QUEUE, ev.SCHED_PREEMPT, ev.SCHED_ADMIT, ev.SCHED_SKIP,
        ev.SCHED_GROW_BACK, ev.SCHED_MIGRATE]
    preempt = actions[1]
    assert preempt["predicted_cost_seconds"] == 60.0
    # measured = the total of the resize the preempt caused (drain ->
    # first resumed step), read from the SAME resize ledger the live
    # cost gate uses
    assert preempt["measured_cost_seconds"] == pytest.approx(42.0)
    # grow-back never completed a resize afterwards: predicted-only
    assert "measured_cost_seconds" not in actions[4]
    # sched_* kinds are their own section, not noise in other_events
    assert not any(k.startswith("sched_") for k in summary["other_events"])


def test_postmortem_renders_scheduler_actions_section():
    records = sorted(_sched_timeline(), key=lambda r: r["ts"])
    out = io.StringIO()
    postmortem.render(postmortem.summarize(records), out)
    text = out.getvalue()
    assert "scheduler actions:" in text
    assert "preempt    victim d/lo -> beneficiary d/hi" in text
    assert "measured 42.0s" in text
    assert "skip       d/h2" in text
    assert "grow back  d/lo  4 -> 8 tpus" in text
    assert "migrate    d/lo rank 0 pod lo-worker-0" in text


def test_postmortem_without_sched_records_has_no_section():
    records = [
        {"ts": 1.0, "event": ev.JOB_CREATED, "host": "c", "job": "d/a"},
        {"ts": 2.0, "event": ev.JOB_SUCCEEDED, "host": "c", "job": "d/a"},
    ]
    summary = postmortem.summarize(records)
    assert summary["scheduler_actions"] == []
    out = io.StringIO()
    postmortem.render(summary, out)
    assert "scheduler actions:" not in out.getvalue()
