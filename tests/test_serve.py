"""Continuous-batching serving engine tests (serve/).

`generate()` is the oracle: a greedy request served through the slot
engine — chunked prefill, per-slot cursors, shared decode step — must be
TOKEN-EXACT against the same request run through the fixed-batch decode
path, on both the dense and Pallas-kernel attention paths. On top of
that: the host-side scheduling policy (chunk planning, FCFS admission,
EOS/length retirement, slot reuse) and the no-recompile contract
(compile counts pinned across traces).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import meta

from mpi_operator_tpu.models import CausalLM, generate, gpt2_config
from mpi_operator_tpu.models.generate import _sample
from mpi_operator_tpu.serve import (
    EngineConfig, Request, Scheduler, ServingEngine, SlotManager,
    plan_chunks, sample_slots,
)

pytestmark = pytest.mark.serving


# ---------------------------------------------------------------------------
# host-side policy (no jax)
# ---------------------------------------------------------------------------

def test_plan_chunks_walks_buckets():
    assert plan_chunks(0, (4, 16)) == []
    assert plan_chunks(16, (4, 16)) == [(0, 16)]
    # full big windows left->right, ragged tail RIGHT-ALIGNED
    assert plan_chunks(36, (4, 16)) == [(0, 16), (16, 16), (32, 4)]
    # tail that fits no small bucket takes the next size up, right-aligned
    assert plan_chunks(37, (4, 16)) == [(0, 16), (16, 16), (21, 16)]
    assert plan_chunks(23, (4, 16)) == [(0, 16), (7, 16)]
    # prompt shorter than every bucket: one window at 0 (engine pads)
    assert plan_chunks(3, (4, 16)) == [(0, 4)]


def test_plan_chunks_covers_exactly():
    # every position < n is written by >= 1 window; a window overruns n
    # ONLY in the pad case (n smaller than the chosen bucket, start 0)
    for n in range(0, 70):
        for buckets in [(8,), (4, 16), (2, 8, 32)]:
            covered = set()
            for start, size in plan_chunks(n, buckets):
                assert size in buckets
                if start + size > n:
                    assert start == 0 and n < size
                covered.update(range(start, start + size))
            assert covered.issuperset(range(n))


def test_scheduler_validates():
    with pytest.raises(ValueError, match="1-3"):
        Scheduler((1, 2, 4, 8), max_len=64)
    with pytest.raises(ValueError, match="ascending"):
        Scheduler((16, 4), max_len=64)
    with pytest.raises(ValueError, match="max_len"):
        Scheduler((128,), max_len=64)
    s = Scheduler((4, 16), max_len=32)
    with pytest.raises(ValueError, match="empty prompt"):
        s.submit(Request(0, [], 4))
    with pytest.raises(ValueError, match="max_new_tokens"):
        s.submit(Request(0, [1], 0))
    with pytest.raises(ValueError, match="max_len"):
        s.submit(Request(0, [1] * 30, 8))


def test_scheduler_fcfs_admission_and_retire():
    s = Scheduler((4,), max_len=32)
    for i in range(3):
        s.submit(Request(i, [1, 2, 3, 4, 5], 4, arrival=float(i)))
    free = [0, 1]
    admitted = s.admit(free, now=10.0)
    assert [st.req.id for st in admitted] == [0, 1] and free == []
    # bonus token: prompt[:-1] prefills, last token is the first input
    assert admitted[0].next_input == 5
    assert admitted[0].chunks == [(0, 4)]
    assert s.admit([], now=10.0) == []         # no slot, no admission
    s.retire(admitted[0])
    third, = s.admit([admitted[0].slot], now=10.0)
    assert third.req.id == 2
    # future arrivals stay queued
    s.submit(Request(9, [1, 2], 2, arrival=99.0))
    assert s.admit([5], now=10.0) == []
    assert s.next_arrival() == 99.0


def test_slot_manager_reuse_and_step_arrays():
    m = SlotManager(2)
    s = Scheduler((4,), max_len=32)
    s.submit(Request(0, list(range(1, 7)), 4))        # needs prefill
    # single-token prompt: no prefill (the bonus token IS the prompt)
    s.submit(Request(1, [8], 4, temperature=0.5, top_k=3, top_p=0.9))
    for st in s.admit(m.free, now=0.0):
        m.bind(st)
    toks, pos, use_prev, temps, top_ks, top_ps, consumers = m.step_arrays()
    # slot 0 is mid-prefill: present in pos, absent from consumers
    assert [st.req.id for st in consumers] == [1]
    assert toks[1] == 8 and temps[1] == np.float32(0.5)
    assert top_ks[1] == 3 and top_ps[1] == np.float32(0.9)
    # first decode step reads the host bonus token, not the device chain
    assert not use_prev[1]
    consumers[0].dispatched = 1
    arrs = m.step_arrays()
    assert arrs[2][1]                 # chained now
    # drained request: stops consuming, awaits its final sync
    consumers[0].dispatched = consumers[0].req.max_new_tokens
    arrs = m.step_arrays()
    assert arrs[-1] == []
    st0, st1 = m.states
    m.release(st0)
    assert m.free == [0] and m.occupied == 1
    with pytest.raises(RuntimeError, match="occupied"):
        m.bind(st1)


# ---------------------------------------------------------------------------
# sample_slots vs generate._sample
# ---------------------------------------------------------------------------

def test_sample_slots_matches_sample_reference():
    """Per-row traced filters == _sample's static filters at the same
    (temperature, top_k, top_p) and the same rng, token for token —
    in both the full-vocab and bounded-pool variants."""
    rng = jax.random.PRNGKey(5)
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64)) * 3.0
    B = logits.shape[0]

    def rows(v, dt=jnp.float32):
        return jnp.full((B,), v, dt)

    for t, k, p in [(0.7, 7, 0.9), (1.3, 3, 1.0), (0.5, 64, 0.85)]:
        ref_tok, ref_lp = _sample(logits, False, jnp.float32(t), rng,
                                  k, p < 1.0, jnp.float32(p))
        for mode in ("full", "bounded"):
            tok, lp = sample_slots(logits, rng, rows(t),
                                   rows(k, jnp.int32), rows(p), mode=mode)
            assert np.array_equal(np.asarray(ref_tok), np.asarray(tok)), \
                (t, k, p, mode)
            np.testing.assert_allclose(np.asarray(ref_lp), np.asarray(lp),
                                       atol=1e-5)
    # greedy rows pick argmax in every mode, logprob from the raw dist
    g_tok, g_lp = _sample(logits, True, jnp.float32(0.0), None, None,
                          False, jnp.float32(1.0))
    for mode in ("greedy", "bounded", "full"):
        tok, lp = sample_slots(logits, rng, rows(0.0),
                               rows(0, jnp.int32), rows(1.0), mode=mode)
        assert np.array_equal(np.asarray(g_tok), np.asarray(tok))
        np.testing.assert_allclose(np.asarray(g_lp), np.asarray(lp),
                                   atol=1e-5)


def test_sample_slots_mixed_rows_independent():
    """Greedy and sampling rows coexist in one call: the greedy row is
    exact argmax, the top_k=1 row degenerates to argmax too."""
    logits = jax.random.normal(jax.random.PRNGKey(2), (3, 32))
    tok, _ = sample_slots(
        logits, jax.random.PRNGKey(0),
        jnp.asarray([0.0, 1.5, 0.8]), jnp.asarray([0, 1, 4], jnp.int32),
        jnp.asarray([1.0, 1.0, 1.0]), mode="bounded")
    am = np.argmax(np.asarray(logits), -1)
    assert int(tok[0]) == am[0]
    assert int(tok[1]) == am[1]          # top_k=1 == greedy
    assert 0 <= int(tok[2]) < 32


# ---------------------------------------------------------------------------
# engine vs generate() (the oracle)
# ---------------------------------------------------------------------------

def _setup(decode_kernel=False, vocab=64, max_len=64, **cfg_kw):
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=vocab, max_len=max_len)
    model = CausalLM(cfg)
    probe = jnp.zeros((1, 4), jnp.int32)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), probe))["params"]
    engine = ServingEngine(model, params, EngineConfig(
        slots=4, chunk_buckets=(4, 8), decode_kernel=decode_kernel,
        **cfg_kw))
    return model, params, engine


def _oracle(model, params, req):
    out = generate(model, params,
                   jnp.asarray([list(req.prompt)], jnp.int32),
                   req.max_new_tokens, eos_id=req.eos_id)
    toks = list(np.asarray(out.tokens[0, len(req.prompt):]))
    if req.eos_id is not None and req.eos_id in toks:
        toks = toks[:toks.index(req.eos_id) + 1]   # engine stops at eos
    return toks


@pytest.mark.parametrize("decode_kernel", [False, True])
def test_engine_single_request_token_exact(decode_kernel):
    model, params, engine = _setup(decode_kernel)
    prompt = list(np.random.RandomState(3).randint(0, 64, (13,)))
    req = Request(0, prompt, max_new_tokens=10)
    res = engine.run([req])
    assert res[0].finish_reason == "length"
    assert res[0].tokens == _oracle(model, params, req)
    assert len(res[0].logprobs) == 10
    assert all(lp <= 0 for lp in res[0].logprobs)
    assert res[0].ttft >= 0 and len(res[0].token_times) == 10


def test_engine_mixed_lengths_match_oracle_per_request():
    """Six greedy requests at six prompt lengths share slots and the
    compiled step; each must still match its own batch-1 generate()."""
    model, params, engine = _setup()
    rs = np.random.RandomState(7)
    reqs = [Request(i, list(rs.randint(0, 64, (p,))), max_new_tokens=n)
            for i, (p, n) in enumerate([(1, 6), (3, 9), (9, 4), (14, 7),
                                        (5, 5), (7, 8)])]
    results = engine.run(reqs)
    assert set(results) == set(range(6))
    for req in reqs:
        assert results[req.id].tokens == _oracle(model, params, req), \
            f"request {req.id} diverged"


def test_engine_eos_retirement_and_slot_reuse():
    """More requests than slots + an eos_id that actually fires: finished
    rows retire, their slots serve later arrivals, every result matches
    the oracle (including the eos cut)."""
    model, params, engine = _setup()
    rs = np.random.RandomState(11)
    probe = Request(99, list(rs.randint(0, 64, (6,))), max_new_tokens=8)
    eos = _oracle(model, params, probe)[2]     # a token greedy WILL emit
    engine.reset()
    reqs = [Request(i, list(rs.randint(0, 64, (3 + i,))),
                    max_new_tokens=8, eos_id=eos)
            for i in range(6)]                 # 6 requests, 4 slots
    results = engine.run(reqs)
    assert len(results) == 6
    assert any(r.finish_reason == "eos" for r in results.values())
    for req in reqs:
        assert results[req.id].tokens == _oracle(model, params, req)
        if results[req.id].finish_reason == "eos":
            assert results[req.id].tokens[-1] == eos


@pytest.mark.parametrize("paged", [False, True])
def test_engine_compile_counts_stay_fixed(paged):
    """The no-recompile contract: after a mixed greedy+sampling trace, a
    reset, and a second different-shape trace, the step has at most one
    program per sample_slots mode and prefill one per bucket. In paged
    mode the reset must ALSO rewind the page allocator and prefix cache
    — a replay of the same trace admits with zero carried-over state
    (and identical tokens), still without recompiling."""
    _, _, engine = _setup(**({"paged": True, "page_size": 8}
                             if paged else {}))
    rs = np.random.RandomState(13)

    def trace(base):
        return [Request(base + i, list(rs.randint(0, 64, (p,))),
                        max_new_tokens=4,
                        temperature=0.9 if i % 2 else 0.0,
                        top_k=5 if i % 2 else 0)
                for i, p in enumerate([2, 6, 9, 13, 4])]

    t0 = trace(0)
    a = engine.run(t0)
    first = engine.compile_counts()
    engine.reset()
    if paged:
        # the allocator rewound with the rest of the serving state:
        # every page free, no refcounts, no cached prefixes (stale K/V
        # must not survive into the zeroed cache)
        alloc = engine.page_allocator
        assert alloc.in_use == 0 and alloc.cached_pages == 0
        assert alloc.available == alloc.usable
        assert alloc.hits == alloc.misses == 0
        alloc.check()
    engine.run(trace(100))
    second = engine.compile_counts()
    assert first == second                    # reset must not recompile
    assert second["step"] <= 3
    assert second["prefill"] <= len(engine.config.chunk_buckets)
    assert second["init_cache"] == 1 and second["cast"] == 1
    engine.reset()
    b = engine.run(t0)                        # identical replay post-reset
    assert engine.compile_counts() == second
    for r in t0:
        assert b[r.id].tokens == a[r.id].tokens


def test_engine_streams_tokens_in_order():
    model, params, engine = _setup()
    req = Request(0, [5, 9, 2], max_new_tokens=6)
    seen = []
    engine.run([req], on_token=lambda r, t: seen.append((r.id, t)))
    assert seen == [(0, t) for t in _oracle(model, params, req)]


def test_engine_rejects_oversized_request():
    _, _, engine = _setup(max_len=64)
    with pytest.raises(ValueError, match="max_len"):
        engine.run([Request(0, [1] * 60, max_new_tokens=10)])


def test_engine_sampling_reproducible_and_in_support():
    """Sampled requests: same seed → same tokens; different engine seed
    diverges; every sampled token is one of the top_k at its position."""
    model, params, engine = _setup()
    prompt = [3, 1, 4, 1, 5]
    req = Request(0, prompt, max_new_tokens=6, temperature=1.2, top_k=3)
    a = engine.run([req])[0].tokens
    engine.reset()
    assert engine.run([req])[0].tokens == a
    other = ServingEngine(model, params, EngineConfig(
        slots=4, chunk_buckets=(4, 8), rng_seed=1))
    b = other.run([req])[0].tokens
    assert len(a) == len(b) == 6
    ctx = list(prompt)
    for t in a:
        logits = np.asarray(model.apply(
            {"params": params}, jnp.asarray([ctx], jnp.int32)))[0, -1]
        assert t in np.argsort(logits)[-3:], "token outside top_k support"
        ctx.append(t)


@pytest.mark.parametrize("decode_kernel", [False, True])
def test_engine_async_matches_sync_token_exact(decode_kernel):
    """The double-buffered loop vs the drain-every-step loop: identical
    greedy tokens (including EOS cuts mid-flight, which cost the async
    loop one discarded junk step), identical finish reasons, and ZERO
    extra compiles — async/sync share the same compiled step."""
    model, params, engine = _setup(decode_kernel)
    rs = np.random.RandomState(11)
    probe = Request(99, list(rs.randint(0, 64, (6,))), max_new_tokens=8)
    eos = _oracle(model, params, probe)[2]     # a token greedy WILL emit
    engine.reset()
    reqs = [Request(i, list(rs.randint(0, 64, (3 + i,))),
                    max_new_tokens=8, eos_id=eos)
            for i in range(6)]                 # 6 requests, 4 slots
    assert engine.config.async_decode          # the default
    a = engine.run(reqs)
    counts_async = engine.compile_counts()
    engine.config.async_decode = False
    engine.reset()
    b = engine.run(reqs)
    assert engine.compile_counts() == counts_async
    assert any(r.finish_reason == "eos" for r in a.values())
    for req in reqs:
        assert a[req.id].tokens == b[req.id].tokens == \
            _oracle(model, params, req), f"request {req.id} diverged"
        assert a[req.id].finish_reason == b[req.id].finish_reason


def test_engine_async_compile_pins_and_sampled_replay():
    """Async mode holds the same no-recompile contract as sync, across
    run -> reset -> run with mixed greedy+sampled traffic; and a reset
    async engine replays its sampled draws exactly (the per-step rng
    counter rewinds with it)."""
    _, _, engine = _setup()
    rs = np.random.RandomState(29)
    reqs = [Request(i, list(rs.randint(0, 64, (p,))),
                    max_new_tokens=5,
                    temperature=1.1 if i % 2 else 0.0,
                    top_k=4 if i % 2 else 0)
            for i, p in enumerate([2, 7, 10, 3, 12])]
    a = engine.run(reqs)
    first = engine.compile_counts()
    engine.reset()
    b = engine.run(reqs)
    second = engine.compile_counts()
    assert first == second                    # reset must not recompile
    assert second["step"] <= 3
    assert second["prefill"] <= len(engine.config.chunk_buckets)
    for req in reqs:                          # sampled draws replay too
        assert a[req.id].tokens == b[req.id].tokens


@pytest.mark.multichip
def test_engine_with_sharded_params_matches_oracle():
    """Serving over dp-sharded params (the bench's deployment shape):
    GSPMD partitions the engine's programs; tokens stay oracle-exact."""
    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.parallel.sharding import shard_init

    if jax.device_count() < 2:
        pytest.skip("needs >1 device")
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=64)
    model = CausalLM(cfg)
    mesh = make_mesh(MeshConfig(dp=jax.device_count()))
    variables, _ = shard_init(model, mesh, jax.random.PRNGKey(0),
                              jnp.zeros((1, 4), jnp.int32))
    params = variables["params"]
    engine = ServingEngine(model, params, EngineConfig(
        slots=2, chunk_buckets=(4, 8)))
    rs = np.random.RandomState(17)
    reqs = [Request(i, list(rs.randint(0, 64, (p,))), max_new_tokens=5)
            for i, p in enumerate([4, 9, 6])]
    results = engine.run(reqs)
    for req in reqs:
        assert results[req.id].tokens == _oracle(model, params, req)


def test_paged_admission_stages_reservations_when_no_slot_free():
    """Slot-aware reserve-ahead: when pages fit but no SLOT is free,
    queued requests park their page reservations in `staged` — the pins
    land before decode churn can evict their prefixes, and when a slot
    frees the head admits off its parked reservation instead of paying
    reservation work on the critical path."""
    from mpi_operator_tpu.serve import PageAllocator

    s = Scheduler((4,), max_len=16)
    a = PageAllocator(20, 4)                  # 19 usable pages
    for i in range(3):
        s.submit(Request(i, [1, 2, 3, 4, 5], 8, arrival=0.0))
    need = Scheduler.pages_needed(s.queue[0], a.page_size)

    avail0 = a.available
    st0, = s.admit([0], now=1.0, allocator=a)
    assert st0.req.id == 0
    # the head consumed the only slot — the SAME admit call already
    # stages the two queued spans behind it
    assert set(s.staged) == {1, 2}
    assert a.available == avail0 - 3 * need
    # idempotent: a slotless pass admits nothing and stages nothing twice
    assert s.admit([], now=1.0, allocator=a) == []
    assert set(s.staged) == {1, 2}
    assert a.available == avail0 - 3 * need

    # a slot frees: the staged head admits, CONSUMING its reservation
    st1, = s.admit([1], now=1.0, allocator=a)
    assert st1.req.id == 1 and 1 not in s.staged
    assert a.available == avail0 - 3 * need       # no double reserve
    assert st1.page_table is not None
    a.check()


def test_reserve_ahead_respects_future_arrivals_and_pool_limits():
    """Staging follows the same gates as admission: requests that have
    not arrived yet are never staged, and a span the pool can't cover
    stays unstaged (no partial pins left behind)."""
    from mpi_operator_tpu.serve import PageAllocator

    s = Scheduler((4,), max_len=16)
    a = PageAllocator(5, 4)                   # 4 usable pages
    s.submit(Request(0, [1, 2, 3, 4, 5], 8, arrival=0.0))   # needs 3 pages
    s.submit(Request(1, [1, 2, 3, 4, 5], 8, arrival=0.0))   # won't fit too
    s.submit(Request(2, [1, 2], 2, arrival=99.0))           # future
    assert s.admit([], now=1.0, allocator=a) == []
    assert set(s.staged) == {0}               # 1 doesn't fit, 2 not arrived
    free_before = a.available
    assert s.admit([], now=1.0, allocator=a) == []
    assert a.available == free_before         # failed fits leak nothing
    a.check()
