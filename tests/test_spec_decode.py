"""Speculative decoding tests (serve/engine.py multi-token verify).

The contract under test: speculation changes WHEN tokens are computed,
never WHICH. A greedy request served with speculative drafting +
batched verify must be BITWISE token-exact against the same request
through the non-speculative engine — in dense and kernel attention, in
contiguous and paged KV (int8 included), across retire/reuse and
reset(). On top of that: the no-recompile contract (at most the two
bucketed verify widths, held across reset + replay), the adversarial
drafter bound (a garbage drafter can waste proposals but never tokens
or extra sweeps), the `rewind` slot primitive, and the benchmark's
ttft == -1.0 timeout sentinel staying out of the latency percentiles.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import meta

from mpi_operator_tpu.models import CausalLM, generate, gpt2_config
from mpi_operator_tpu.serve import (
    EngineConfig, Request, Scheduler, ServingEngine, SlotManager,
    propose_ngram,
)

pytestmark = [pytest.mark.serving, pytest.mark.spec]


# ---------------------------------------------------------------------------
# propose_ngram: host-side prompt-lookup drafting (no jax)
# ---------------------------------------------------------------------------

def test_propose_ngram_copies_after_the_match():
    # suffix [1,2,3] matched at the start; the k tokens after it follow
    assert propose_ngram([1, 2, 3, 4, 1, 2, 3], k=3) == [4, 1, 2]


def test_propose_ngram_prefers_the_most_recent_occurrence():
    # suffix [5,6] occurs at s=1 (followed by 9) and s=4 (followed by
    # 8): recency wins — the latest occurrence predicts a repeating tail
    assert propose_ngram([7, 5, 6, 9, 5, 6, 8, 5, 6], k=2) == [8, 5]


def test_propose_ngram_clamps_at_history_end():
    assert propose_ngram([1, 2, 1, 2], k=5) == [1, 2]


def test_propose_ngram_novel_text_returns_empty():
    assert propose_ngram([1, 2, 3, 4], k=4) == []
    assert propose_ngram([1], k=4) == []
    assert propose_ngram([1, 1, 1], k=0) == []


# ---------------------------------------------------------------------------
# rewind: the cursor-rollback slot primitive (no jax)
# ---------------------------------------------------------------------------

def _bound_state():
    m = SlotManager(2)
    s = Scheduler((4,), max_len=64)
    s.submit(Request(0, list(range(1, 7)), 8))
    st, = s.admit(m.free, now=0.0)
    m.bind(st)
    return m, st


def test_rewind_moves_the_cursor_back():
    m, st = _bound_state()
    st.pos = 10
    m.rewind(st.slot, 3)
    assert st.pos == 7
    m.rewind(st.slot, 0)                     # no-op rewind is legal
    assert st.pos == 7


def test_rewind_validates_slot_and_bounds():
    m, st = _bound_state()
    st.pos = 2
    with pytest.raises(ValueError, match="negative"):
        m.rewind(st.slot, -1)
    with pytest.raises(ValueError, match="< 0"):
        m.rewind(st.slot, 3)                 # underflow past 0
    free = next(i for i in range(len(m.states)) if m.states[i] is None)
    with pytest.raises(ValueError, match="free slot"):
        m.rewind(free, 1)


def test_rewind_crosses_unpublished_page_boundaries():
    # a rejected span that crossed into a fresh page rolls back across
    # the boundary; the page stays allocated (inside the reserved span)
    m, st = _bound_state()
    st.pos = 20
    st.published_pages = 1
    m.rewind(st.slot, 11, page_size=8)       # 20 -> 9, across 16
    assert st.pos == 9


def test_rewind_refuses_to_unpublish_pages():
    # published pages are immutable prefix-cache entries other requests
    # may share: the cursor may land ON the frontier, never below it
    m, st = _bound_state()
    st.pos = 20
    st.published_pages = 2                   # frontier = 16 at page 8
    m.rewind(st.slot, 4, page_size=8)
    assert st.pos == 16
    with pytest.raises(ValueError, match="un-publish"):
        m.rewind(st.slot, 1, page_size=8)


# ---------------------------------------------------------------------------
# engine: greedy exactness across attention/KV modes
# ---------------------------------------------------------------------------

def _setup(decode_kernel=False, vocab=64, max_len=64, kv_cache_dtype=None,
           drafter=None, **cfg_kw):
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=vocab, max_len=max_len,
                      kv_cache_dtype=kv_cache_dtype)
    model = CausalLM(cfg)
    probe = jnp.zeros((1, 4), jnp.int32)
    params = meta.unbox(model.init(jax.random.PRNGKey(0), probe))["params"]
    engine = ServingEngine(model, params, EngineConfig(
        slots=4, chunk_buckets=(4, 8), decode_kernel=decode_kernel,
        **cfg_kw), drafter=drafter)
    return model, params, engine


def _trace(seed=11, n=8, sampled=False):
    # 8 requests over 4 slots: the second wave reuses retired slots, so
    # exactness covers retire/reuse, not just a single resident batch
    rs = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        temp = 0.9 if (sampled and i % 2) else 0.0
        p = int(rs.choice([2, 5, 9, 13]))
        reqs.append(Request(i, list(rs.randint(0, 64, (p,))),
                            max_new_tokens=int(rs.choice([5, 8, 12])),
                            temperature=temp, top_k=4 if temp else 0))
    return reqs


def _nospec_rerun(engine, reqs):
    """Replay `reqs` through the SAME engine with speculation off
    (same compiled step/prefill programs — the A/B is pure policy)."""
    mode = engine.config.speculative
    engine.config.speculative = None
    engine.reset()
    base = engine.run(reqs)
    engine.config.speculative = mode
    return base


def _oracle(model, params, req):
    out = generate(model, params,
                   jnp.asarray([list(req.prompt)], jnp.int32),
                   req.max_new_tokens, eos_id=req.eos_id)
    return list(np.asarray(out.tokens[0, len(req.prompt):]))


@pytest.mark.parametrize("decode_kernel,engine_kw", [
    (False, {}),
    (True, {}),
    (False, dict(paged=True, page_size=8)),
    (True, dict(paged=True, page_size=8)),
], ids=["dense", "kernel", "paged", "paged-kernel"])
def test_spec_greedy_token_exact_across_modes(decode_kernel, engine_kw):
    _, _, engine = _setup(decode_kernel, speculative="ngram", **engine_kw)
    reqs = _trace()
    spec = engine.run(reqs)
    stats = engine.spec_stats()
    assert stats["proposed"] > 0             # speculation actually ran
    base = _nospec_rerun(engine, reqs)
    for r in reqs:
        assert spec[r.id].tokens == base[r.id].tokens, f"request {r.id}"
        assert spec[r.id].finish_reason == base[r.id].finish_reason
        assert np.allclose(spec[r.id].logprobs, base[r.id].logprobs,
                           atol=1e-5)


def test_spec_single_request_matches_generate_oracle():
    model, params, engine = _setup(speculative="ngram")
    prompt = list(np.random.RandomState(3).randint(0, 64, (13,)))
    req = Request(0, prompt, max_new_tokens=10)
    res = engine.run([req])
    assert res[0].tokens == _oracle(model, params, req)
    assert len(res[0].logprobs) == 10
    assert all(lp <= 0 for lp in res[0].logprobs)
    assert res[0].ttft >= 0 and len(res[0].token_times) == 10


@pytest.mark.parametrize("engine_kw", [
    {}, dict(paged=True, page_size=8)], ids=["contiguous", "paged"])
def test_spec_int8_kv_cache_token_exact(engine_kw):
    _, _, engine = _setup(kv_cache_dtype="int8", speculative="ngram",
                          **engine_kw)
    reqs = _trace(seed=17)
    spec = engine.run(reqs)
    assert engine.spec_stats()["proposed"] > 0
    base = _nospec_rerun(engine, reqs)
    for r in reqs:
        assert spec[r.id].tokens == base[r.id].tokens, f"request {r.id}"


def test_spec_mixed_sampling_rows_ride_along():
    # every other request samples — sampled rows never draft but share
    # the verify batch; greedy rows stay exact vs the non-spec engine,
    # and the whole mixed trace replays exactly across reset (the
    # per-step rng counter rewinds with it)
    _, _, engine = _setup(speculative="ngram")
    reqs = _trace(seed=31, sampled=True)
    a = engine.run(reqs)
    assert engine.spec_stats()["proposed"] > 0
    first = engine.compile_counts()
    engine.reset()
    b = engine.run(reqs)
    # the mixed batch holds the same pins: no recompile on replay
    assert engine.compile_counts() == first
    for r in reqs:
        assert a[r.id].tokens == b[r.id].tokens
    base = _nospec_rerun(engine, reqs)
    for r in reqs:
        if r.temperature == 0.0:
            assert a[r.id].tokens == base[r.id].tokens, f"request {r.id}"


# ---------------------------------------------------------------------------
# the no-recompile contract: <= 2 bucketed verify widths
# ---------------------------------------------------------------------------

def test_spec_reset_replay_holds_the_verify_compile_pins():
    _, _, engine = _setup(speculative="ngram", paged=True, page_size=8)
    # draft_k=4 buckets: a narrow width-2 program + the full k+1
    assert engine._verify_buckets == (2, 5)
    reqs = _trace(seed=23)
    a = engine.run(reqs)
    first = engine.compile_counts()
    assert 1 <= first["verify"] <= len(engine._verify_buckets)
    engine.reset()
    b = engine.run(reqs)
    assert engine.compile_counts() == first  # replay: zero new compiles
    for r in reqs:
        assert a[r.id].tokens == b[r.id].tokens


def test_spec_verify_widths_bucket_a_mixed_budget_trace():
    # the draft budget clamps k: wave 1 (max_new=2, budget 1) drafts
    # exactly one token — the narrow width-2 program; wave 2 drafts the
    # full draft_k — width 5. Two widths ran, exactly the two bucketed
    # programs compiled, and the trace stays token-exact. (A drafter
    # that always fills its budget makes the width choice
    # deterministic; ngram proposal lengths are trace-dependent.)
    _, _, engine = _setup(speculative="draft",
                          drafter=lambda hist, k: [int(hist[-1])] * k)
    rs = np.random.RandomState(5)
    reqs = [Request(i, [1 + i, 2, 3], max_new_tokens=2)
            for i in range(4)]
    reqs += [Request(4 + i, list(rs.randint(0, 64, (6,))),
                     max_new_tokens=12) for i in range(4)]
    spec = engine.run(reqs)
    assert engine.compile_counts()["verify"] == 2
    base = _nospec_rerun(engine, reqs)
    for r in reqs:
        assert spec[r.id].tokens == base[r.id].tokens, f"request {r.id}"


def test_spec_composes_with_disagg_decode_pool():
    # speculation lives in the decode pool: the prefill pool strips the
    # knob (it never decodes, so it never drafts or verifies), the
    # decode pool drafts/verifies under its own compile pins, and the
    # disaggregated output stays token-identical to the colocated
    # speculative engine
    from mpi_operator_tpu.serve import DisaggEngine

    model, params, coloc = _setup(speculative="ngram", paged=True,
                                  page_size=8)
    disagg = DisaggEngine(model, params, EngineConfig(
        slots=4, chunk_buckets=(4, 8), paged=True, page_size=8,
        speculative="ngram"))
    reqs = _trace(seed=53, n=6)
    a = coloc.run(reqs)
    b = disagg.run(reqs)
    assert disagg.decode.spec_stats()["proposed"] > 0
    counts = disagg.compile_counts()
    assert counts["prefill_pool"]["verify"] == 0
    assert counts["prefill_pool"]["step"] == 0
    assert counts["decode_pool"]["prefill"] == 0
    assert 1 <= counts["decode_pool"]["verify"] <= 2
    for r in reqs:
        assert a[r.id].tokens == b[r.id].tokens, f"request {r.id}"


# ---------------------------------------------------------------------------
# drafter plug-in mode + adversarial drafters
# ---------------------------------------------------------------------------

def test_spec_draft_mode_shares_the_verify_path():
    # a pluggable drafter (here: the ngram proposer as a callable) rides
    # the exact same verify/accept path as the built-in mode
    _, _, engine = _setup(speculative="draft",
                          drafter=lambda hist, k: propose_ngram(hist, k))
    reqs = _trace(seed=47)
    spec = engine.run(reqs)
    stats = engine.spec_stats()
    assert stats["proposed"] > 0
    assert stats["acceptance_rate"] > 0
    base = _nospec_rerun(engine, reqs)
    for r in reqs:
        assert spec[r.id].tokens == base[r.id].tokens, f"request {r.id}"


def test_spec_adversarial_drafter_exact_and_never_more_sweeps():
    # a drafter that only proposes one constant token: it can waste
    # proposals but never tokens — output stays exact, and the verify
    # loop never takes MORE sequential sweeps than plain sync decode
    # takes steps (every verify banks at least its bonus token)
    _, _, engine = _setup(speculative="draft",
                          drafter=lambda hist, k: [63] * k)
    reqs = _trace(seed=41)
    adv = engine.run(reqs)
    assert engine.spec_stats()["proposed"] > 0
    adv_steps = engine._steps_dispatched
    engine.config.speculative = None
    engine.config.async_decode = False
    engine.reset()
    base = engine.run(reqs)
    for r in reqs:
        assert adv[r.id].tokens == base[r.id].tokens, f"request {r.id}"
    assert adv_steps <= engine._steps_dispatched
    engine.config.async_decode = True
    engine.config.speculative = "draft"


def test_spec_out_of_vocab_drafter_ids_are_truncated():
    # garbage ids out of [0, vocab) truncate at the first bad token —
    # nothing out-of-range ever reaches the device gather
    _, _, engine = _setup(speculative="draft",
                          drafter=lambda hist, k: [10 ** 9, -1, 3])
    reqs = _trace(seed=43, n=4)
    res = engine.run(reqs)
    base = _nospec_rerun(engine, reqs)
    for r in reqs:
        assert res[r.id].tokens == base[r.id].tokens, f"request {r.id}"


def test_spec_telemetry_federates_into_job_series():
    # engine-side spec counters/histograms export as tpu_worker_* and
    # federate into the tpu_job_* aggregate like every other series
    from mpi_operator_tpu.telemetry import WorkerTelemetry
    from mpi_operator_tpu.telemetry.collector import MetricsFederation
    from mpi_operator_tpu.telemetry.prometheus import render_registry

    wtel = WorkerTelemetry()
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=64)
    model = CausalLM(cfg)
    params = meta.unbox(model.init(jax.random.PRNGKey(0),
                                   jnp.zeros((1, 4), jnp.int32)))["params"]
    engine = ServingEngine(model, params, EngineConfig(
        slots=2, chunk_buckets=(4, 8), speculative="ngram"),
        telemetry=wtel.serving)
    engine.run([Request(0, [1, 2, 3, 1, 2, 3], max_new_tokens=8)])
    stats = engine.spec_stats()
    assert stats["proposed"] > 0
    fed = MetricsFederation("sjob", clock=lambda: 0.0)
    fed.ingest(0, render_registry(wtel.registry))
    text = "\n".join(fed.render_lines())
    for series, expect in [("tpu_job_spec_proposed_total",
                            float(stats["proposed"])),
                           ("tpu_job_spec_accepted_total",
                            float(stats["accepted"]))]:
        line = next(ln for ln in text.splitlines()
                    if ln.startswith(series))
        assert float(line.rsplit(" ", 1)[1]) == expect, line
    assert "tpu_job_spec_tokens_per_step_bucket" in text
    assert "tpu_job_spec_acceptance_ratio_bucket" in text


def test_spec_config_validation():
    with pytest.raises(ValueError, match="speculative"):
        _setup(speculative="turbo")
    with pytest.raises(ValueError, match="draft_k"):
        _setup(speculative="ngram", draft_k=0)
    with pytest.raises(ValueError, match="drafter"):
        _setup(speculative="draft")


# ---------------------------------------------------------------------------
# benchmark: the ttft == -1.0 timeout sentinel stays out of percentiles
# ---------------------------------------------------------------------------

class _FakeResult:
    def __init__(self, ttft, token_times):
        self.ttft = ttft
        self.token_times = token_times


def test_ttft_sentinel_never_pollutes_latency_percentiles():
    from mpi_operator_tpu.examples.serve_benchmark import (
        _latency_fields, _percentiles)
    # pure all-timeout trace: every request expired before its first
    # token — all-None fields, no crash, no -1 folded in as a latency
    pure = _latency_fields([_FakeResult(-1.0, [])] * 4)
    assert pure == {"serving_ttft_p50_ms": None,
                    "serving_ttft_p99_ms": None,
                    "serving_tpot_p50_ms": None,
                    "serving_tpot_p99_ms": None}
    assert _percentiles([]) == {50: None, 99: None}
    # mixed trace: the sentinel is EXCLUDED, not clamped — percentiles
    # reflect only requests that produced a first token
    mixed = _latency_fields(
        [_FakeResult(-1.0, []), _FakeResult(0.5, [0.5, 0.6])])
    assert mixed["serving_ttft_p50_ms"] == mixed["serving_ttft_p99_ms"] \
        == 500.0
    assert mixed["serving_tpot_p50_ms"] == 100.0


def test_all_timeout_engine_trace_reports_without_crashing():
    from mpi_operator_tpu.examples.serve_benchmark import _latency_fields
    # integration: a real engine run under request_timeout=0 retires
    # everything with finish_reason "timeout"; the benchmark's latency
    # assembly must survive it with no negative field
    _, _, engine = _setup(speculative=None, request_timeout=0.0,
                          paged=True, page_size=8)
    reqs = [Request(i, [1 + i, 2, 3, 4, 5, 6], 8) for i in range(3)]
    results = engine.run(reqs)
    assert all(r.finish_reason == "timeout" for r in results.values())
    # the sentinel fires exactly when no token was emitted
    assert all((r.ttft == -1.0) == (not r.token_times)
               for r in results.values())
    fields = _latency_fields(results.values())
    for key, val in fields.items():
        assert val is None or val >= 0.0, (key, val)
