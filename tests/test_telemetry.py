"""Data-plane telemetry tests (telemetry/).

Four contracts, each with a real failure mode behind it:

- **Histogram buckets**: fixed log-spaced edges, human-readable `le`
  labels, no dropped observations (below-lo and above-hi both land),
  percentile estimates inside the documented ~26% relative-error bound.
- **Prometheus text format**: what an actual Prometheus scraper
  requires — HELP/TYPE once per name and before any sample, cumulative
  non-decreasing buckets with +Inf == _count, the versioned content
  type, and label-value escaping (a quote in a label must corrupt one
  label, not the whole scrape).
- **Event-log durability**: every emit is individually fsync'd, so a
  SIGKILL mid-write leaves all completed records parseable (torn final
  line tolerated, mid-file corruption skipped with a counted warning —
  a postmortem must see the records AROUND the bad line). Size-based
  rotation keeps bounded disk, and the reader spans the whole chain.
- **Hot-loop cost**: the per-step recorder overhead, measured in
  isolation, stays under 1% of a REAL measured CPU-smoke step time —
  the telemetry must not move the numbers it reports. The same live
  run also proves /metrics is scrapeable MID-RUN and that train and
  serve series coexist in one registry scrape.
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from mpi_operator_tpu.telemetry import (
    CONTENT_TYPE, Counter, EventLog, Histogram, Registry, TelemetryServer,
    TrainTelemetry, WorkerTelemetry, escape_label_value, read_events,
    render_registry,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# histogram buckets
# ---------------------------------------------------------------------------

def test_histogram_default_edges():
    h = Histogram("h")
    # 1e-4 .. 1e3 at 10/decade: 7 decades * 10 + 1 = 71 edges
    assert len(h.edges) == 71
    assert h.edges[0] == 1e-4
    assert h.edges[-1] == 1000.0
    # strictly increasing with ~10^(1/10) ratio despite the 6-sig-fig
    # rounding that keeps `le` labels readable
    for lo, hi in zip(h.edges, h.edges[1:]):
        assert lo < hi
        assert 1.20 < hi / lo < 1.32
    # readable labels: no float-repr tails like 0.00012589254117941674
    assert all(len(repr(e)) <= 12 for e in h.edges)


def test_histogram_env_knobs_override_range(monkeypatch):
    """TPU_HIST_LO/HI/PER_DECADE re-range every histogram at construction
    time — a deploy-time knob, no code change."""
    monkeypatch.setenv("TPU_HIST_LO", "1e-2")
    monkeypatch.setenv("TPU_HIST_HI", "1e1")
    monkeypatch.setenv("TPU_HIST_PER_DECADE", "2")
    h = Histogram("h", lo=1e-4, hi=1e3)   # code values lose to the env
    assert h.edges == (0.01, 0.0316228, 0.1, 0.316228, 1.0, 3.16228, 10.0)
    # empty string behaves like unset: code values win again
    monkeypatch.setenv("TPU_HIST_LO", "")
    monkeypatch.setenv("TPU_HIST_HI", "")
    monkeypatch.setenv("TPU_HIST_PER_DECADE", "")
    d = Histogram("d")
    assert d.edges[0] == 1e-4 and d.edges[-1] == 1000.0 and len(d.edges) == 71
    # a knob that doesn't parse fails loudly, not as a silent default
    monkeypatch.setenv("TPU_HIST_LO", "fast")
    with pytest.raises(ValueError):
        Histogram("bad")


def test_histogram_no_observation_dropped():
    h = Histogram("h", lo=1e-3, hi=1e1)
    h.observe(1e-9)          # below lo -> first bucket
    h.observe(5e5)           # above hi -> overflow (+Inf) bucket
    h.observe(0.02)
    counts, total, count = h.snapshot()
    assert count == 3 and sum(counts) == 3
    assert counts[0] == 1 and counts[-1] == 1
    assert total == pytest.approx(1e-9 + 5e5 + 0.02)


def test_histogram_le_semantics():
    """A value exactly on an edge counts into that edge's bucket (the
    Prometheus `le` = less-or-equal convention)."""
    h = Histogram("h", lo=1.0, hi=100.0, per_decade=1)
    assert h.edges == (1.0, 10.0, 100.0)
    h.observe(10.0)
    counts, _, _ = h.snapshot()
    assert counts[1] == 1


def test_histogram_percentile_error_bound():
    h = Histogram("h")
    for v in (0.002, 0.004, 0.008, 0.016, 0.5):
        h.observe(v)
    assert h.percentile(0) is not None
    # median of the five is 0.008; the estimate may be off by the edge
    # ratio but no more
    assert h.percentile(50) == pytest.approx(0.008, rel=0.27)
    assert h.percentile(99) == pytest.approx(0.5, rel=0.27)
    assert Histogram("empty").percentile(50) is None


def test_histogram_observe_n_matches_repeated_observe():
    a, b = Histogram("a"), Histogram("b")
    a.observe_n(0.031, 7)
    for _ in range(7):
        b.observe(0.031)
    ca, sa, na = a.snapshot()
    cb, sb, nb = b.snapshot()
    assert ca == cb and na == nb == 7
    assert sa == pytest.approx(sb)    # one multiply vs seven adds
    a.observe_n(1.0, 0)               # no-op, not a crash
    assert a.count == 7


def test_registry_get_or_create_and_kind_conflict():
    reg = Registry()
    c1 = reg.counter("x_total", "help")
    assert reg.counter("x_total") is c1          # same series accumulates
    assert reg.counter("x_total", labels={"k": "v"}) is not c1
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        Counter("c").inc(-1)


# ---------------------------------------------------------------------------
# Prometheus text format
# ---------------------------------------------------------------------------

def _sample_registry():
    reg = Registry()
    reg.counter("tpu_worker_reqs_total", "requests").inc(3)
    reg.counter("tpu_worker_reqs_total", "requests",
                labels={"phase": 'we"ird\nphase\\'}).inc(1)
    reg.gauge("tpu_worker_depth", "queue depth").set(2.5)
    h = reg.histogram("tpu_worker_lat_seconds", "latency")
    for v in (0.001, 0.02, 0.02, 5000.0):
        h.observe(v)
    return reg


def test_render_registry_is_valid_prometheus_text():
    body = render_registry(_sample_registry())
    lines = body.splitlines()
    assert body.endswith("\n")

    seen_samples, helped, typed = set(), set(), set()
    for ln in lines:
        if ln.startswith("# HELP"):
            name = ln.split()[2]
            assert name not in helped, "duplicate HELP"
            assert name not in seen_samples, "HELP after samples"
            helped.add(name)
        elif ln.startswith("# TYPE"):
            name = ln.split()[2]
            assert name not in typed, "duplicate TYPE"
            assert name not in seen_samples, "TYPE after samples"
            typed.add(name)
        else:
            base = ln.split("{")[0].split()[0]
            for suffix in ("_bucket", "_sum", "_count"):
                if base.endswith(suffix):
                    base = base[:-len(suffix)]
                    break
            seen_samples.add(base)
    # every sample family carries its HELP/TYPE pair
    assert seen_samples <= helped and seen_samples <= typed

    # cumulative buckets: non-decreasing, +Inf equals _count
    buckets = [ln for ln in lines
               if ln.startswith("tpu_worker_lat_seconds_bucket")]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts)
    assert buckets[-1].startswith('tpu_worker_lat_seconds_bucket{le="+Inf"}')
    total = next(int(ln.rsplit(" ", 1)[1]) for ln in lines
                 if ln.startswith("tpu_worker_lat_seconds_count"))
    assert counts[-1] == total == 4

    # escaping: the raw quote/newline/backslash never appear unescaped
    weird = next(ln for ln in lines if "phase=" in ln)
    assert '\\"' in weird and "\\n" in weird and "\\\\" in weird
    assert "\n" not in weird


def test_escape_label_value():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert escape_label_value("plain") == "plain"


def test_telemetry_server_scrape_and_health():
    reg = _sample_registry()
    healthy = {"ok": True}
    srv = TelemetryServer(reg, port=0, healthy=lambda: healthy["ok"])
    try:
        base = f"http://127.0.0.1:{srv.port}"
        resp = urllib.request.urlopen(base + "/metrics")
        assert resp.status == 200
        assert resp.headers["Content-Type"] == CONTENT_TYPE
        body = resp.read().decode()
        assert "tpu_worker_lat_seconds_bucket" in body
        assert urllib.request.urlopen(base + "/healthz").status == 200
        healthy["ok"] = False
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/healthz")
        assert exc.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/nope")
        assert exc.value.code == 404
    finally:
        srv.close()
        srv.close()          # idempotent


# ---------------------------------------------------------------------------
# event log durability
# ---------------------------------------------------------------------------

def test_event_log_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "sub" / "events.jsonl")   # parent auto-created
    with EventLog(path, clock=lambda: 42.0) as ev:
        ev.emit("preemption_drain", step=5)
        ev.emit("emergency_checkpoint", step=5, train_dir="/x")
    # a torn FINAL line (crash mid-write) must not hide complete records
    with open(path, "a") as f:
        f.write('{"ts": 43.0, "event": "emergency_ch')
    records = read_events(path)
    assert [r["event"] for r in records] == ["preemption_drain",
                                             "emergency_checkpoint"]
    assert records[0] == {"ts": 42.0, "event": "preemption_drain", "step": 5}
    assert read_events(path, kind="emergency_checkpoint")[0]["step"] == 5


def test_event_log_mid_file_corruption_skipped_with_warning(tmp_path, caplog):
    """Mid-file garbage (disk bitrot, concurrent writer) must not hide
    the records AROUND it from a postmortem: the reader skips ANY
    undecodable line, warns, and counts it — loud in logs, not fatal."""
    from mpi_operator_tpu.telemetry import events as events_mod
    path = str(tmp_path / "events.jsonl")
    with open(path, "w") as f:
        f.write('{"ts": 1.0, "event": "a"}\nGARBAGE\n{"ts": 2.0, "event": "b"}\n')
    before = events_mod.DECODE_ERRORS
    with caplog.at_level("WARNING", logger=events_mod.logger.name):
        records = read_events(path)
    assert [r["event"] for r in records] == ["a", "b"]
    assert events_mod.DECODE_ERRORS == before + 1
    assert any("undecodable" in r.message for r in caplog.records)


def test_event_log_rotation_bounded_and_reader_spans_chain(tmp_path):
    """TPU_EVENTS_MAX_BYTES rotation: the live file stays under the cap,
    old segments shift .1 -> .2 with keep-last-N pruning, and
    read_events stitches the WHOLE chain oldest-first — a record must
    not vanish from a postmortem just because it rotated."""
    from mpi_operator_tpu.telemetry.events import event_files
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, clock=lambda: 1.0, max_bytes=256, keep=2) as ev:
        for i in range(40):
            ev.emit("slot_admit", request=i)
        names = [os.path.basename(p) for p in event_files(path)]
        # oldest segment first, live file last
        assert names[-1] == "events.jsonl"
        assert len(names) == 3                      # keep=2 + live
        assert os.path.getsize(path) <= 256
    records = read_events(path)
    reqs = [r["request"] for r in records]
    # pruning dropped the oldest, but what remains is contiguous,
    # ordered, and ends with the newest record
    assert reqs == list(range(reqs[0], 40))
    assert len(reqs) > 3                            # spans > 1 file


def test_event_log_rotation_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("TPU_EVENTS_MAX_BYTES", raising=False)
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as ev:
        for i in range(200):
            ev.emit("slot_admit", request=i)
    assert not os.path.exists(path + ".1")
    assert len(read_events(path)) == 200


def test_event_log_bind_stamps_replica_labels(tmp_path):
    """TrainTelemetry(labels=...) paths emit through a BOUND view: every
    record from a packed/fused replica carries its replica (and
    pack_group) so one shared events.jsonl stays attributable."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, clock=lambda: 7.0) as ev:
        packed = ev.bind(pack_group="sweep")
        r0 = packed.bind(replica="0")
        r1 = packed.bind(replica="1")
        r0.emit("replica_frozen", step=3)
        r1.emit("divergence_rollback", from_step=4, to_step=2)
        ev.emit("checkpoint_saved", step=5)          # unbound: no labels
        r0.emit("slot_admit", replica="9")           # explicit field wins
    records = read_events(path)
    assert records[0]["pack_group"] == "sweep"
    assert records[0]["replica"] == "0"
    assert records[1]["replica"] == "1"
    assert "replica" not in records[2]
    assert records[3]["replica"] == "9"
    # bound views share the ONE underlying fsync'd file
    assert all(r["ts"] == 7.0 for r in records)


def test_event_log_survives_sigkill_mid_write(tmp_path):
    """The acceptance shape of the fsync discipline: a child emitting
    events as fast as it can, SIGKILLed the instant the first record is
    durable, leaves a parseable log. Loads events.py by file path so the
    child pays no jax import."""
    path = str(tmp_path / "events.jsonl")
    child = (
        "import importlib.util, sys\n"
        "spec = importlib.util.spec_from_file_location('ev', %r)\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        "log = mod.EventLog(%r)\n"
        "i = 0\n"
        "while True:\n"
        "    log.emit('slot_admit', request=i, slot=i %% 8)\n"
        "    if i == 0:\n"
        "        print('READY', flush=True)\n"
        "    i += 1\n"
    ) % (os.path.join(REPO, "mpi_operator_tpu", "telemetry", "events.py"),
         path)
    proc = subprocess.Popen([sys.executable, "-c", child],
                            stdout=subprocess.PIPE)
    try:
        assert proc.stdout.readline().strip() == b"READY"
        deadline = time.monotonic() + 10
        while os.path.getsize(path) < 2000:       # let writes pile up
            assert time.monotonic() < deadline, "child wrote too slowly"
            time.sleep(0.01)
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    records = read_events(path)
    assert len(records) >= 10
    assert [r["request"] for r in records] == list(range(len(records)))
    assert all(r["event"] == "slot_admit" for r in records)


# ---------------------------------------------------------------------------
# live worker /metrics + overhead pin (one compile, shared fixture)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def live_run():
    """A real CPU-smoke LM train run feeding a served WorkerTelemetry,
    scraped MID-RUN from a step hook; then a real serving-engine trace on
    the SAME registry. Yields (mid-run scrape body, final scrape body,
    train metrics dict)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax.core import meta

    from mpi_operator_tpu.models.transformer import CausalLM, gpt2_config
    from mpi_operator_tpu.parallel import MeshConfig, make_mesh
    from mpi_operator_tpu.serve import EngineConfig, Request, ServingEngine
    from mpi_operator_tpu.train.lm_trainer import LMTrainer, LMTrainerConfig

    wtel = WorkerTelemetry()
    port = wtel.serve(port=0).port
    base = f"http://127.0.0.1:{port}/metrics"

    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=64)
    tr = LMTrainer(CausalLM(cfg), make_mesh(MeshConfig(dp=8)),
                   LMTrainerConfig(global_batch_size=8, seq_len=16,
                                   log_every=2))
    state = tr.init_state(jax.random.PRNGKey(0))
    toks = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64),
        tr.batch_sharding)
    batch = (toks, jnp.roll(toks, -1, 1))

    class Stream:
        def __iter__(self):
            while True:
                yield batch

    mid = {}

    def hook(_state, step):
        # after the first window fetch (log_every=2) the gauges are hot;
        # scrape while the loop is still dispatching steps
        if "body" not in mid and step >= 4:
            mid["body"] = urllib.request.urlopen(base).read().decode()

    state, metrics = tr.benchmark(state, Stream(), num_steps=8,
                                  warmup_steps=1, log=lambda s: None,
                                  step_hook=hook, telemetry=wtel.train)

    # serve leg on the SAME registry: params straight from a fresh init
    params = meta.unbox(CausalLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)))["params"]
    engine = ServingEngine(CausalLM(cfg), params,
                           EngineConfig(slots=2, chunk_buckets=(4, 8),
                                        decode_kernel=False),
                           telemetry=wtel.serving)
    prompts = np.random.RandomState(0).randint(0, 64, (2, 6))
    engine.run([Request(i, list(p), max_new_tokens=4)
                for i, p in enumerate(prompts)])

    final = urllib.request.urlopen(base).read().decode()
    try:
        yield mid.get("body"), final, metrics
    finally:
        wtel.close()


def test_metrics_scrapeable_mid_run(live_run):
    mid_body, _, _ = live_run
    assert mid_body is not None, "step hook never scraped"
    assert "tpu_worker_step_seconds_bucket" in mid_body
    # by step 4 two windows have landed: counts are moving, not zero
    count = next(int(ln.rsplit(" ", 1)[1])
                 for ln in mid_body.splitlines()
                 if ln.startswith("tpu_worker_step_seconds_count"))
    assert count >= 2
    assert "tpu_worker_tokens_per_sec" in mid_body
    assert "tpu_worker_mfu" in mid_body


def test_one_scrape_serves_train_and_serve_series(live_run):
    _, final, _ = live_run
    for series in ("tpu_worker_step_seconds_count",     # train
                   "tpu_worker_steps_total",
                   "tpu_worker_goodput",
                   "tpu_worker_host_gap_seconds_count", # both legs feed it
                   "tpu_worker_ttft_seconds_count",     # serve
                   "tpu_worker_decode_step_seconds_count",
                   "tpu_worker_requests_total"):
        assert series in final, f"missing {series}"
    sample = {ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
              for ln in final.splitlines() if not ln.startswith("#")}
    assert sample["tpu_worker_steps_total"] == 8
    assert sample["tpu_worker_ttft_seconds_count"] == 2
    assert sample["tpu_worker_requests_total"] == 2
    assert sample["tpu_worker_tokens_total"] == 8       # 2 reqs x 4 new
    assert sample["tpu_worker_slots"] == 2


def test_benchmark_metrics_carry_step_percentiles(live_run):
    _, _, metrics = live_run
    assert metrics["step_time_p50_ms"] > 0
    assert metrics["step_time_p99_ms"] >= metrics["step_time_p50_ms"]
    # the host-gap histogram (time blocked on the window's device fetch)
    # rides along in the same summary
    assert metrics["host_gap_p50_ms"] > 0
    assert metrics["host_gap_p99_ms"] >= metrics["host_gap_p50_ms"]
    assert metrics["goodput"] == 1.0


def test_recorder_overhead_under_one_percent(live_run):
    """The per-step instrument cost — span enter/exit plus the window
    ops amortized over log_every — measured in ISOLATION, must stay
    under 1% of the real measured step time from the same smoke run.
    (Isolation, not A/B loop timing: two full train runs differ by more
    than 1% from compile-cache and allocator noise alone, which would
    drown exactly the signal this pins.)"""
    from mpi_operator_tpu.telemetry import TrainTelemetry, span

    tel = TrainTelemetry()
    log_every = 10
    n = 3000
    t0 = time.perf_counter()
    for i in range(n):
        with span("train.step"):
            pass
        if i % log_every == 0:
            tel.observe_steps(0.005, log_every)
            tel.update_window(tokens_per_sec=1e5, mfu=0.4)
            tel.record_streak(0)
    per_step_overhead = (time.perf_counter() - t0) / n

    _, _, metrics = live_run
    step_seconds = metrics["step_time_p50_ms"] / 1e3
    assert per_step_overhead < 0.01 * step_seconds, (
        f"recorder costs {per_step_overhead * 1e6:.1f} µs/step against a "
        f"{step_seconds * 1e3:.2f} ms step — over the 1% budget")


# ---------------------------------------------------------------------------
# shutdown ordering
# ---------------------------------------------------------------------------

def test_worker_close_flushes_events_before_server_teardown(tmp_path):
    """WorkerTelemetry.close flushes the event log FIRST; with
    close_events=False the borrowed log stays open for its owner."""
    path = str(tmp_path / "events.jsonl")
    ev = EventLog(path)
    wtel = WorkerTelemetry(events=ev)
    wtel.serve(port=0)
    ev.emit("preemption_drain", step=3)
    wtel.close(close_events=False)
    assert not ev._fh.closed                       # still the owner's
    ev.emit("emergency_checkpoint", step=3)        # owner can keep writing
    ev.close()
    assert [r["event"] for r in read_events(path)] == [
        "preemption_drain", "emergency_checkpoint"]


def test_resilience_context_flushes_events_on_exit(tmp_path):
    """The __exit__ ordering contract: events are flushed before any
    teardown, so a drain record emitted in the dying breath of a
    preempted run is durable."""
    from mpi_operator_tpu.train.resilience import (
        ResilienceConfig, ResilienceContext)

    path = str(tmp_path / "events.jsonl")
    ev = EventLog(path)
    ctx = ResilienceContext(ResilienceConfig(), log=lambda s: None,
                            events=ev)
    with ctx:
        ev.emit("preemption_drain", step=1)
    assert read_events(path, kind="preemption_drain")
    ev.close()


# ---------------------------------------------------------------------------
# labeled series (the per-replica/job view under HFTA packing)
# ---------------------------------------------------------------------------

def test_labeled_series_are_isolated_per_label_set():
    """Same metric NAME, different label sets → independent instruments;
    same (name, labels) → the same instrument back (accumulation, not
    collision)."""
    reg = Registry()
    c0 = reg.counter("tpu_worker_steps_total", labels={"replica": "0"})
    c1 = reg.counter("tpu_worker_steps_total", labels={"replica": "1"})
    bare = reg.counter("tpu_worker_steps_total")
    assert c0 is not c1 and c0 is not bare
    assert reg.counter("tpu_worker_steps_total",
                       labels={"replica": "0"}) is c0
    c0.inc(3)
    c1.inc(5)
    assert (c0.value, c1.value, bare.value) == (3, 5, 0)
    text = render_registry(reg)
    assert 'tpu_worker_steps_total{replica="0"} 3' in text
    assert 'tpu_worker_steps_total{replica="1"} 5' in text
    # HELP/TYPE once per NAME even with several label sets
    assert text.count("# TYPE tpu_worker_steps_total counter") == 1
    # kind conflicts stay conflicts per label set
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("tpu_worker_steps_total", labels={"replica": "0"})


def test_labeled_histogram_cumulative_and_inf_per_series():
    """Every labeled histogram series is independently cumulative with
    its own +Inf bucket == its own _count."""
    reg = Registry()
    h0 = reg.histogram("tpu_worker_step_seconds", lo=0.01, hi=10.0,
                       labels={"replica": "0"})
    h1 = reg.histogram("tpu_worker_step_seconds", lo=0.01, hi=10.0,
                       labels={"replica": "1"})
    for v in (0.02, 0.2, 2.0):
        h0.observe(v)
    h1.observe(0.5)
    text = render_registry(reg)
    assert ('tpu_worker_step_seconds_bucket{replica="0",le="+Inf"} 3'
            in text)
    assert ('tpu_worker_step_seconds_bucket{replica="1",le="+Inf"} 1'
            in text)
    assert 'tpu_worker_step_seconds_count{replica="0"} 3' in text
    assert 'tpu_worker_step_seconds_count{replica="1"} 1' in text
    # per-series cumulative monotonicity
    for rep, total in (("0", 3), ("1", 1)):
        cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
                if line.startswith("tpu_worker_step_seconds_bucket")
                and f'replica="{rep}"' in line]
        assert cums == sorted(cums) and cums[-1] == total


def test_label_values_escaped_in_render():
    reg = Registry()
    g = reg.gauge("tpu_worker_goodput",
                  labels={"job": 'swe"ep\\1\nx'})
    g.set(1.0)
    text = render_registry(reg)
    assert 'job="swe\\"ep\\\\1\\nx"' in text
    # the escape helper round-trips the canonical cases
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"


def test_train_telemetry_labels_flow_to_all_instruments():
    """TrainTelemetry(labels=...) threads the label set onto every
    instrument it owns — two replica bundles on ONE registry scrape as
    disjoint labeled series."""
    reg = Registry()
    t0 = TrainTelemetry(reg, labels={"replica": "0"})
    t1 = TrainTelemetry(reg, labels={"replica": "1"})
    t0.observe_steps(0.1, 2)
    t1.observe_steps(0.2, 4)
    t0.update_window(tokens_per_sec=100.0)
    t1.update_window(tokens_per_sec=50.0)
    assert t0.steps_total.value == 2 and t1.steps_total.value == 4
    text = render_registry(reg)
    assert 'tpu_worker_steps_total{replica="0"} 2' in text
    assert 'tpu_worker_steps_total{replica="1"} 4' in text
    assert 'tpu_worker_tokens_per_sec{replica="0"} 100' in text
    assert 'tpu_worker_tokens_per_sec{replica="1"} 50' in text
