"""Per-request distributed tracing tests (telemetry/trace.py and its
serving/federation/postmortem integrations).

The contracts pinned here, in dependency order: the sampled-out path
allocates nothing (head sampling is one hash + compare, deterministic
across processes); hop chains are contiguous by construction so hop
durations sum to the root's end-to-end seconds; a failover replay joins
the ONE existing root instead of opening a second; the fsync'd sink
tolerates torn tails like every other event log; TraceFederation
re-ingests idempotently and feeds slowest-trace exemplars to the
autoscaler, whose breach decisions the postmortem pairs with rendered
hop trees ("exemplar pending" when the trace was sampled out).
"""
import json

import pytest

from mpi_operator_tpu.telemetry.trace import (
    REQUEST_ROOT, SESSION_ROOT, SPAN, TRACE_HOP_BUCKETS, Tracer,
    _mix64, build_trees, hop_name, hop_percentiles, hop_spans,
    orphan_spans, read_trace_spans, render_tree, trace_sum_gap,
)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def test_sampling_deterministic_across_tracers():
    a, b = Tracer(sample=0.5), Tracer(sample=0.5)
    kept = [i for i in range(200) if a.sampled(i)]
    assert kept == [i for i in range(200) if b.sampled(i)]
    # rate=0.5 keeps roughly half — the hash is uniform enough that a
    # 200-id draw can't collapse to nothing or everything
    assert 50 < len(kept) < 150


def test_sampled_out_allocates_nothing():
    t = Tracer(sample=0.0)
    assert t.begin_request(123, 0.0) is None
    # the off-path pin: no RequestTrace, no registry entry, no record
    assert t.open_requests() == []
    assert len(t.ring) == 0
    # sample=1.0 never consults the hash
    assert Tracer(sample=1.0).sampled(123)


def test_force_sample_overrides_rate():
    t = Tracer(sample=0.0)
    t.force_sample(7)
    rt = t.begin_request(7, 0.0)
    assert rt is not None
    rt.finish("ok", 1.0)
    assert len(t.ring) == 1 and t.ring[0]["trace"] == 7


def test_mix64_is_stable():
    # the splitmix64 finalizer must never drift: every pod keeps the
    # SAME id subset or cross-pod trees stop reconstructing
    assert _mix64(0) == 0
    assert _mix64(1) == _mix64(1)
    assert _mix64(1) != _mix64(2)


# ---------------------------------------------------------------------------
# hop chains
# ---------------------------------------------------------------------------

def test_hops_are_contiguous_and_sum_to_root():
    t = Tracer(sample=1.0)
    rt = t.begin_request(1, 10.0, replica=0)
    rt.begin_hop("router.queue_wait", 10.0)
    rt.begin_hop("serve.admission", 10.5)
    rt.begin_hop("serve.prefill", 10.6)
    rt.begin_hop("serve.decode", 11.0)
    rt.finish("ok", 12.0)
    tree = build_trees(t.ring)[1]
    assert tree["root"]["name"] == REQUEST_ROOT
    assert tree["root"]["status"] == "ok"
    assert tree["root"]["seconds"] == pytest.approx(2.0)
    hops = [s for s in tree["spans"] if s["parent"] is not None]
    assert [hop_name(s) for s in hops] == [
        "queue_wait", "admission", "prefill", "decode"]
    # contiguity: each hop starts where the previous ended
    for prev, nxt in zip(hops, hops[1:]):
        assert prev["t0"] + prev["seconds"] == pytest.approx(nxt["t0"])
    assert trace_sum_gap(tree) == pytest.approx(0.0, abs=1e-6)


def test_hop_attrs_land_on_open_hop():
    t = Tracer(sample=1.0)
    rt = t.begin_request(1, 0.0)
    rt.begin_hop("serve.kv_handoff", 0.0)
    rt.hop_attrs(pages=3, cached_pages=1)
    rt.begin_hop("serve.decode", 0.5)
    rt.finish("ok", 1.0)
    hop = next(s for s in t.ring if s["name"] == "serve.kv_handoff")
    assert hop["attrs"] == {"pages": 3, "cached_pages": 1}


def test_failover_replay_joins_the_one_root():
    t = Tracer(sample=1.0)
    rt = t.begin_request(5, 0.0)
    rt.begin_hop("serve.admission", 0.0)
    # replica dies: the open hop closes as a failover casualty, the
    # root stays open for the replay
    rt.abandon(0.4)
    rt.event("failover", replica=0)
    again = t.begin_request(5, 99.0)       # fresh Request, SAME id
    assert again is rt
    again.begin_hop("router.queue_wait", 0.4)
    again.begin_hop("serve.decode", 0.7)
    again.finish("ok", 1.0)
    tree = build_trees(t.ring)[5]
    roots = [s for s in tree["spans"] if s["parent"] is None]
    assert len(roots) == 1
    assert roots[0]["events"] == [{"name": "failover", "replica": 0}]
    statuses = [s["status"] for s in tree["spans"]
                if s["parent"] is not None]
    assert statuses.count("failover") == 1
    # the replay reopened at the abandon instant: still gap-free
    assert trace_sum_gap(tree) == pytest.approx(0.0, abs=1e-6)
    assert t.open_requests() == []


def test_finish_is_idempotent():
    t = Tracer(sample=1.0)
    rt = t.begin_request(1, 0.0)
    rt.finish("timeout", 2.0)
    rt.finish("ok", 3.0)                   # loses: first terminal wins
    roots = [s for s in t.ring if s["parent"] is None]
    assert len(roots) == 1 and roots[0]["status"] == "timeout"


def test_session_spans_parent_batch_children():
    t = Tracer(sample=1.0)
    ss = t.begin_session(0.0, replica=1)
    assert ss.trace < 0                    # never collides with request ids
    ss.child("serve.decode_step", 0.1, 0.05, batch=4)
    ss.end(1.0)
    tree = build_trees(t.ring)[ss.trace]
    assert tree["root"]["name"] == SESSION_ROOT
    kids = [s for s in tree["spans"] if s["parent"] is not None]
    assert kids[0]["name"] == "serve.decode_step"
    assert kids[0]["attrs"] == {"batch": 4}
    # session spans are NOT request hops
    assert hop_spans(t.ring) == []


# ---------------------------------------------------------------------------
# sink + analysis
# ---------------------------------------------------------------------------

def test_sink_survives_torn_tail(tmp_path):
    path = str(tmp_path / "traces.jsonl")
    with Tracer(path=path, sample=1.0) as t:
        rt = t.begin_request(1, 0.0)
        rt.begin_hop("serve.decode", 0.0)
        rt.finish("ok", 1.0)
    with open(path, "a") as f:
        f.write('{"event": "span", "trace": 9, "span"')   # torn write
    spans = read_trace_spans(path)
    assert [s["trace"] for s in spans] == [1, 1]
    assert all(s["event"] == SPAN for s in spans)


def test_build_trees_dedups_and_finds_orphans():
    root = {"trace": 1, "span": 1, "parent": None, "name": REQUEST_ROOT,
            "t0": 0.0, "seconds": 1.0, "status": "ok"}
    hop = {"trace": 1, "span": 2, "parent": 1, "name": "serve.decode",
           "t0": 0.0, "seconds": 1.0, "status": "ok"}
    stray = {"trace": 2, "span": 3, "parent": 99, "name": "serve.decode",
             "t0": 0.0, "seconds": 0.5, "status": "ok"}
    # the same records twice — a re-read / re-ingest — keeps one copy
    trees = build_trees([root, hop, stray, root, hop])
    assert len(trees[1]["spans"]) == 2
    assert orphan_spans([root, hop, stray]) == [stray]
    assert trace_sum_gap(trees[2]) is None    # rootless: no verdict


def test_hop_percentiles_shape():
    spans = []
    for i, secs in enumerate([0.001, 0.002, 0.004, 0.1]):
        spans.append({"trace": i, "span": 2 * i + 1, "parent": 2 * i,
                      "name": "serve.decode", "t0": 0.0,
                      "seconds": secs, "status": "ok"})
    out = hop_percentiles(spans)
    assert set(out) == {"decode_p50_ms", "decode_p99_ms"}
    assert out["decode_p50_ms"] <= out["decode_p99_ms"]
    assert out["decode_p99_ms"] == pytest.approx(100.0)


def test_render_tree_lines():
    t = Tracer(sample=1.0)
    rt = t.begin_request(1, 0.0)
    rt.event("shed", reason="no capacity")
    rt.begin_hop("serve.kv_handoff", 0.0)
    rt.hop_attrs(pages=2)
    rt.finish("timeout", 0.5)
    lines = render_tree(build_trees(t.ring)[1])
    assert lines[0].startswith("serve.request 500.0ms status=timeout")
    assert any(line.strip().startswith("@ shed") for line in lines)
    assert any("pages=2" in line for line in lines)


# ---------------------------------------------------------------------------
# engine integration (the real serving path)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_model():
    import jax
    import jax.numpy as jnp
    from flax.core import meta

    from mpi_operator_tpu.models import CausalLM, gpt2_config
    cfg = gpt2_config("test", attention="dense", dtype=jnp.float32,
                      vocab_size=64, max_len=64)
    model = CausalLM(cfg)
    probe = jnp.zeros((1, 8), jnp.int32)
    params = meta.unbox(
        model.init(jax.random.PRNGKey(0), probe))["params"]
    return model, params


def _requests(n=3):
    from mpi_operator_tpu.serve import Request
    return [Request(i, [(7 * i + j) % 60 + 1 for j in range(6)], 3)
            for i in range(n)]


@pytest.mark.serving
def test_engine_traces_sum_to_e2e(small_model):
    from mpi_operator_tpu.serve import EngineConfig, ServingEngine
    model, params = small_model
    tracer = Tracer(sample=1.0)
    eng = ServingEngine(model, params,
                        EngineConfig(slots=2, chunk_buckets=(8,)),
                        tracer=tracer)
    results = eng.run(_requests())
    assert tracer.open_requests() == []
    assert orphan_spans(tracer.ring) == []
    trees = build_trees(tracer.ring)
    for rid in results:
        tree = trees[rid]
        assert tree["root"]["status"] == "ok"
        names = [hop_name(s) for s in tree["spans"]
                 if s["parent"] is not None]
        assert names[0] == "admission" and names[-1] == "decode"
        assert trace_sum_gap(tree) <= max(
            0.005, 0.02 * tree["root"]["seconds"])
    # the engine session root parents the batch-level decode steps
    sessions = [s for s in tracer.ring if s["trace"] < 0]
    assert any(s["name"] == "serve.decode_step" for s in sessions)
    assert any(s["name"] == SESSION_ROOT for s in sessions)


@pytest.mark.serving
def test_tracing_never_changes_tokens_or_pins(small_model):
    from mpi_operator_tpu.serve import EngineConfig, ServingEngine
    model, params = small_model
    cfg = EngineConfig(slots=2, chunk_buckets=(8,))
    plain = ServingEngine(model, params, cfg)
    traced = ServingEngine(model, params, cfg, tracer=Tracer(sample=1.0))
    want = {rid: r.tokens for rid, r in plain.run(_requests()).items()}
    got = {rid: r.tokens for rid, r in traced.run(_requests()).items()}
    assert got == want                      # greedy: bitwise identical
    assert traced.compile_counts() == plain.compile_counts()


@pytest.mark.serving
def test_disagg_handoff_hop_carries_pages(small_model):
    from mpi_operator_tpu.serve import DisaggEngine, EngineConfig
    model, params = small_model
    tracer = Tracer(sample=1.0)
    eng = DisaggEngine(
        model, params,
        EngineConfig(slots=2, chunk_buckets=(8,), paged=True,
                     page_size=8, num_pages=32),
        tracer=tracer)
    results = eng.run(_requests(2))
    trees = build_trees(tracer.ring)
    pages = 0
    for rid in results:
        names = [hop_name(s) for s in trees[rid]["spans"]
                 if s["parent"] is not None]
        assert "prefill" in names and "kv_handoff" in names \
            and "decode" in names
        for s in trees[rid]["spans"]:
            if s["parent"] is not None and hop_name(s) == "kv_handoff":
                pages += s["attrs"]["pages"]
    assert pages > 0                        # the handoff actually moved KV


# ---------------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------------

def _span_batch(trace, seconds, ts=1000.0):
    return [
        {"event": SPAN, "ts": ts, "trace": trace, "span": 2 * trace,
         "parent": None, "name": REQUEST_ROOT, "t0": 0.0,
         "seconds": seconds, "status": "ok"},
        {"event": SPAN, "ts": ts, "trace": trace, "span": 2 * trace + 1,
         "parent": 2 * trace, "name": "serve.decode", "t0": 0.0,
         "seconds": seconds, "status": "ok"},
    ]


def test_federation_ingest_is_idempotent():
    from mpi_operator_tpu.telemetry.collector import TraceFederation
    fed = TraceFederation("j", clock=lambda: 1000.0)
    batch = _span_batch(1, 0.25)
    assert fed.ingest("pod-0", batch) == 2
    assert fed.ingest("pod-0", batch) == 0       # re-scrape: no-op
    assert fed.hops["decode"]["count"] == 1
    # the SAME span ids from another pod are distinct evidence (a
    # cross-pod tree's pieces arrive from different files)
    assert fed.ingest("pod-1", batch) == 2
    tree = fed.tree(1)
    assert tree["root"] is not None and len(tree["spans"]) == 2


def test_federation_offset_corrects_wall_ts_only():
    from mpi_operator_tpu.telemetry.collector import TraceFederation
    fed = TraceFederation("j", clock=lambda: 1000.0)
    fed.ingest("pod-0", _span_batch(1, 0.25, ts=990.0), offset=10.0)
    span = fed.spans[1][0]
    assert span["ts"] == pytest.approx(1000.0)
    assert span["ts_raw"] == pytest.approx(990.0)
    assert span["seconds"] == pytest.approx(0.25)   # durations untouched


def test_federation_exemplars_slowest_first():
    from mpi_operator_tpu.telemetry.collector import TraceFederation
    fed = TraceFederation("j", clock=lambda: 1000.0)
    for trace, secs in [(1, 0.1), (2, 0.9), (3, 0.4)]:
        fed.ingest("pod-0", _span_batch(trace, secs))
    assert fed.slowest_trace() == 2
    assert [t for _s, t in fed.exemplars()] == [2, 3, 1]
    # outside the window the pool drains
    late = TraceFederation("j", clock=lambda: 5000.0, window=600.0)
    late.ingest("pod-0", _span_batch(4, 1.0, ts=1000.0))
    assert late.slowest_trace() is None


def test_federation_histogram_lines():
    from mpi_operator_tpu.telemetry.collector import TraceFederation
    fed = TraceFederation("j", clock=lambda: 1000.0)
    fed.ingest("pod-0", _span_batch(1, 0.003))
    lines = fed.render_lines()
    text = "\n".join(lines)
    assert '# TYPE tpu_job_trace_hop_seconds histogram' in text
    assert 'tpu_job_trace_hop_seconds_count{job="j",hop="decode"} 1' \
        in text
    # cumulative buckets: every edge >= 0.005 counts the 3ms decode
    assert 'le="0.005"} 1' in text and 'le="0.001"} 0' in text
    assert len([ln for ln in lines if "_bucket" in ln]) \
        == len(TRACE_HOP_BUCKETS) + 1


def test_observatory_push_ingests_like_a_scrape():
    from mpi_operator_tpu.telemetry.collector import JobObservatory
    now = [100.0]
    obs = JobObservatory(clock=lambda: now[0])
    payload = {
        "now": 100.0,
        "metrics": ("# TYPE tpu_worker_tokens_total counter\n"
                    "tpu_worker_tokens_total 5\n"),
        "events": [{"ts": 99.0, "event": "serve_started"}],
        "traces": _span_batch(3, 0.7, ts=99.5),
    }
    assert obs.ingest_push("job", 0, payload, serving=True)
    view = obs.view("job")
    assert view["federation"].observed_tokens() == 5
    assert obs.slowest_trace("job") == 3
    assert view["worker_records"]["push-0"][0]["event"] == "serve_started"
    # the push advanced the serving progress lease exactly like a scrape
    assert view["progress_step"] == 5 and view["progress_ts"] == 100.0
    # federated render carries the trace histograms
    assert any("tpu_job_trace_hop_seconds" in ln
               for ln in view["traces"].render_lines())


def test_observatory_push_rides_the_fault_injector():
    from mpi_operator_tpu.telemetry.chaos import ScrapeFaultInjector
    from mpi_operator_tpu.telemetry.collector import JobObservatory
    obs = JobObservatory(clock=lambda: 100.0,
                         scrape_injector=ScrapeFaultInjector(["*/fail=1"]))
    ok = obs.ingest_push("job", 0, {"now": 100.0, "metrics": ""})
    assert not ok                            # the injected fault dropped it
    view = obs.view("job")
    assert view["federation"].pods[0]["failures"] == 1
    assert obs.scrape_injector.fault_count("fail") == 1


# ---------------------------------------------------------------------------
# autoscaler exemplar threading + postmortem pairing
# ---------------------------------------------------------------------------

def test_breach_decision_carries_exemplar():
    from mpi_operator_tpu.api.types import ServingSLO
    from mpi_operator_tpu.controller.autoscale import (
        DecodeAutoscaler, SLOObservation)
    slo = ServingSLO(ttft_p99_seconds=0.5, breach_seconds=10.0,
                     cooldown_floor_seconds=0.0)
    scaler = DecodeAutoscaler(slo)
    bad = SLOObservation(ttft_p99=2.0, exemplar_trace=42)
    assert scaler.decide(0.0, bad, 1, None, None).target is None
    d = scaler.decide(20.0, bad, 1, None, None)
    assert d.target == 2 and d.exemplar_trace == 42
    # a hold decision never exemplifies
    calm = SLOObservation(ttft_p99=0.1, exemplar_trace=42)
    assert scaler.decide(30.0, calm, 2, None, None).exemplar_trace is None


def test_postmortem_renders_exemplar_tree_or_pending(tmp_path):
    import io

    from mpi_operator_tpu.postmortem import render, summarize
    records = [
        {"ts": 0.0, "event": "job_created", "job": "j"},
        {"ts": 5.0, "event": "autoscale_breach", "job": "j", "target": 2,
         "reason": "ttft_p99 2 > 0.5", "exemplar_trace": 7},
        # sampled out: the breach recorded no trace id
        {"ts": 9.0, "event": "request_timeout", "job": "j", "request": 3},
    ]
    summary = summarize(records)
    assert [b["trace"] for b in summary["slo_breaches"]] == [7, None]

    tracer = Tracer(sample=1.0)
    rt = tracer.begin_request(7, 0.0)
    rt.begin_hop("serve.decode", 0.0)
    rt.finish("ok", 1.5)
    trees = build_trees(tracer.ring)

    out = io.StringIO()
    render(summary, out, trees=trees)
    text = out.getvalue()
    assert "slow traces:" in text
    assert "serve.request 1500.0ms" in text        # exemplar hop tree
    assert "exemplar pending (no trace id attached" in text
    # with no trace file at all, the breach with an id degrades to the
    # other pending message instead of crashing
    out2 = io.StringIO()
    render(summary, out2, trees={})
    assert "exemplar pending (trace 7 not in the trace file" \
        in out2.getvalue()


def test_postmortem_cli_reads_trace_file(tmp_path):
    import subprocess
    import sys

    timeline = tmp_path / "timeline.jsonl"
    with open(timeline, "w") as f:
        for rec in [
            {"ts": 0.0, "event": "job_created", "job": "j"},
            {"ts": 5.0, "event": "autoscale_breach", "job": "j",
             "reason": "ttft", "exemplar_trace": 7},
            {"ts": 9.0, "event": "job_succeeded", "job": "j"},
        ]:
            f.write(json.dumps(rec) + "\n")
    traces = tmp_path / "traces.jsonl"
    with Tracer(path=str(traces), sample=1.0) as t:
        rt = t.begin_request(7, 0.0)
        rt.begin_hop("serve.decode", 0.0)
        rt.finish("ok", 0.25)
    proc = subprocess.run(
        [sys.executable, "-m", "mpi_operator_tpu.postmortem",
         str(timeline), "--traces", str(traces)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "slow traces:" in proc.stdout
    assert "serve.decode" in proc.stdout
