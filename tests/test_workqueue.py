"""Workqueue edge cases the controller's safety story leans on.

ref contract (k8s.io/client-go/util/workqueue, SURVEY §5):
- a key being processed is never handed to a second worker; an add()
  during processing marks it dirty and done() re-queues it exactly once;
- add_rate_limited backs off exponentially per item; forget() resets;
- shut_down() wakes every blocked get(), which drains to None.
"""
import threading
import time

import pytest

from mpi_operator_tpu.cluster.workqueue import (
    RateLimitingQueue,
    meta_namespace_key,
    split_key,
)


# ---------------------------------------------------------------------------
# add-while-processing: dirty/processing set semantics
# ---------------------------------------------------------------------------

def test_add_while_processing_requeues_on_done():
    q = RateLimitingQueue()
    q.add("ns/a")
    key = q.get(timeout=0.1)
    assert key == "ns/a"
    # the informer saw another event mid-sync: the key must not be handed
    # to a second worker NOW...
    q.add("ns/a")
    assert q.get(timeout=0.02) is None
    # ...but done() must hand it straight back (the re-sync the event
    # demanded), exactly once
    q.done("ns/a")
    assert q.get(timeout=0.1) == "ns/a"
    q.done("ns/a")
    assert q.get(timeout=0.02) is None


def test_duplicate_adds_coalesce_while_queued():
    q = RateLimitingQueue()
    for _ in range(5):
        q.add("ns/a")
    assert q.get(timeout=0.1) == "ns/a"
    q.done("ns/a")
    assert q.get(timeout=0.02) is None


def test_done_without_pending_add_does_not_requeue():
    q = RateLimitingQueue()
    q.add("ns/a")
    assert q.get(timeout=0.1) == "ns/a"
    q.done("ns/a")
    assert q.get(timeout=0.02) is None


# ---------------------------------------------------------------------------
# per-item exponential backoff + forget
# ---------------------------------------------------------------------------

def test_backoff_grows_exponentially_and_caps():
    q = RateLimitingQueue(base_delay=0.01, max_delay=0.04)
    delays = []
    for _ in range(4):
        before = time.monotonic()
        q.add_rate_limited("ns/a")
        got = q.get(timeout=2.0)        # blocks until the delay elapses
        delays.append(time.monotonic() - before)
        assert got == "ns/a"
        q.done("ns/a")
    # 0.01, 0.02, 0.04, then capped at max_delay 0.04
    assert delays[0] >= 0.01
    assert delays[1] >= 0.02
    assert delays[2] >= 0.04
    assert delays[3] >= 0.04
    assert delays[3] < 0.08 + 0.05      # cap held (scheduling slack)
    assert q.num_requeues("ns/a") == 4


def test_backoff_is_per_item():
    q = RateLimitingQueue(base_delay=0.01)
    for _ in range(3):
        q.add_rate_limited("ns/flaky")
    q.add_rate_limited("ns/fresh")
    assert q.num_requeues("ns/flaky") == 3
    assert q.num_requeues("ns/fresh") == 1


def test_forget_resets_the_backoff_counter():
    q = RateLimitingQueue(base_delay=0.005)
    for _ in range(6):
        q.add_rate_limited("ns/a")
    assert q.num_requeues("ns/a") == 6
    q.forget("ns/a")
    assert q.num_requeues("ns/a") == 0
    # the next failure starts the ladder from the bottom again
    before = time.monotonic()
    q.add_rate_limited("ns/a")
    # drain the earlier queued copies first, then the fresh one
    while q.get(timeout=1.0) is not None:
        q.done("ns/a")
        if time.monotonic() - before > 1.0:
            pytest.fail("queue never drained")
    assert q.num_requeues("ns/a") == 1


def test_add_after_does_not_touch_failures():
    q = RateLimitingQueue()
    q.add_after("ns/a", 0.01)
    assert q.num_requeues("ns/a") == 0
    assert q.get(timeout=1.0) == "ns/a"
    q.done("ns/a")
    # and a non-positive delay enqueues immediately
    q.add_after("ns/a", 0)
    assert q.get(timeout=0.1) == "ns/a"


# ---------------------------------------------------------------------------
# shutdown drains blocked getters
# ---------------------------------------------------------------------------

def test_shutdown_wakes_every_blocked_getter():
    q = RateLimitingQueue()
    results = []
    barrier = threading.Barrier(4)

    def worker():
        barrier.wait()
        results.append(q.get(timeout=5.0))   # blocks: queue is empty

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    barrier.wait()
    time.sleep(0.05)                         # let all three block in get()
    q.shut_down()
    for t in threads:
        t.join(timeout=2.0)
        assert not t.is_alive(), "getter still blocked after shut_down"
    assert results == [None, None, None]


def test_shutdown_rejects_new_work():
    q = RateLimitingQueue()
    q.shut_down()
    q.add("ns/a")
    q.add_after("ns/b", 0.001)
    assert len(q) == 0
    assert q.get(timeout=0.05) is None


def test_snapshot_reports_wedge_evidence():
    q = RateLimitingQueue()
    q.add("ns/queued")
    q.add("ns/stuck")
    assert q.get(timeout=0.1) in ("ns/queued", "ns/stuck")
    q.add_rate_limited("ns/angry")
    snap = q.snapshot()
    assert len(snap["processing"]) == 1      # done() never called: wedged
    assert snap["failures"] == {"ns/angry": 1}
    assert "ns/angry" in snap["waiting"] or "ns/angry" in snap["queue"]


# ---------------------------------------------------------------------------
# add_after coalescing: duplicate wake-ups collapse to the EARLIEST
# deadline (the fleet scheduler arms a wake per skip decision every
# sync — without coalescing each sync would stack another timer)
# ---------------------------------------------------------------------------

def test_add_after_duplicates_coalesce_to_earliest():
    q = RateLimitingQueue()
    q.add_after("ns/a", 0.2)
    q.add_after("ns/a", 0.02)       # earlier deadline must win
    before = time.monotonic()
    assert q.get(timeout=1.0) == "ns/a"
    assert time.monotonic() - before < 0.15
    q.done("ns/a")
    # ONE delivery total: the superseded 0.2s timer must not fire again
    assert q.get(timeout=0.3) is None
    assert len(q) == 0


def test_add_after_later_deadline_is_a_noop():
    q = RateLimitingQueue()
    q.add_after("ns/a", 0.02)
    q.add_after("ns/a", 30.0)       # must NOT push the wake out
    before = time.monotonic()
    assert q.get(timeout=1.0) == "ns/a"
    assert time.monotonic() - before < 0.5
    q.done("ns/a")
    assert q.get(timeout=0.1) is None
    assert len(q) == 0              # no ghost waiting entry left behind


def test_add_after_waiting_len_and_snapshot_truthful():
    q = RateLimitingQueue()
    q.add_after("ns/a", 30.0)
    q.add_after("ns/a", 60.0)
    q.add_after("ns/b", 30.0)
    # two keys waiting, however many timers were armed
    assert len(q) == 2
    snap = q.snapshot()
    assert snap["waiting"] == ["ns/a", "ns/b"]
    # re-arming one of them to (almost) now delivers it without
    # disturbing the other key's pending wake
    q.add_after("ns/a", 0.001)
    assert q.get(timeout=0.5) == "ns/a"
    q.done("ns/a")
    assert len(q) == 1
    assert q.snapshot()["waiting"] == ["ns/b"]


# ---------------------------------------------------------------------------
# key helpers
# ---------------------------------------------------------------------------

def test_split_key_roundtrip_and_validation():
    class Meta:
        namespace, name = "ns", "job"

    class Obj:
        metadata = Meta()

    key = meta_namespace_key(Obj())
    assert key == "ns/job"
    assert split_key(key) == ("ns", "job")
    for bad in ("no-slash", "a/b/c", "/name", "ns/"):
        with pytest.raises(ValueError):
            split_key(bad)
